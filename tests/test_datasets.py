"""Dataset fetchers + record readers (DataVec bridge). Mirrors reference
datasets/datavec tests: CSV classification/regression, sequence reader
with masks, fetcher shapes, normalizer-through-iterator path."""
import os

import numpy as np
import pytest

from deeplearning4j_tpu.datasets import (CifarDataSetIterator,
                                         CollectionRecordReader,
                                         CSVRecordReader,
                                         CSVSequenceRecordReader,
                                         CurvesDataSetIterator,
                                         LFWDataSetIterator,
                                         RecordReaderDataSetIterator,
                                         SequenceRecordReaderDataSetIterator)

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures")


class TestFetchers:
    def test_cifar_shapes(self):
        it = CifarDataSetIterator(32, num_examples=96)
        total = 0
        for ds in it:
            assert ds.features.shape[1:] == (32, 32, 3)
            assert ds.labels.shape[1] == 10
            total += ds.num_examples()
        assert total == 96
        assert it.synthetic   # no local data in this environment

    def test_curves_autoencoder_targets(self):
        it = CurvesDataSetIterator(50, num_examples=100)
        ds = it.next_batch()
        assert ds.features.shape == (50, 784)
        assert np.array_equal(ds.features, ds.labels)  # reconstruction task
        assert ds.features.max() == 1.0

    def test_lfw_shapes(self):
        it = LFWDataSetIterator(16, num_examples=32, num_classes=5)
        ds = it.next_batch()
        assert ds.features.shape == (16, 64, 64, 3)
        assert ds.labels.shape == (16, 5)

    def test_cifar_real_pickle_parser(self, monkeypatch):
        """The cifar-10-batches-py pickle branch runs against the committed
        format-exact fixture slice (tests/fixtures/README_datasets.md) —
        reference CifarDataSetIterator.java real-data path."""
        monkeypatch.setenv("DL4J_TPU_CIFAR_DIR",
                           os.path.join(FIXTURES, "cifar10"))
        it = CifarDataSetIterator(8, train=True, shuffle=False)
        assert not it.synthetic
        total, seen_labels = 0, set()
        for ds in it:
            assert ds.features.shape[1:] == (32, 32, 3)
            assert ds.features.dtype == np.float32
            assert float(ds.features.max()) <= 1.0
            assert ds.labels.shape[1] == 10
            seen_labels |= set(np.argmax(np.asarray(ds.labels), 1).tolist())
            total += ds.num_examples()
        assert total == 20          # 5 train batches x 4 fixture rows
        assert len(seen_labels) > 1
        te = CifarDataSetIterator(8, train=False, shuffle=False)
        assert not te.synthetic
        assert te.next_batch().num_examples() == 4

    def test_lfw_real_imagedir_parser(self, monkeypatch):
        """The person-directory JPEG branch runs against the committed
        fixture (2 people x 2 images) — reference LFWDataSetIterator.java."""
        monkeypatch.setenv("DL4J_TPU_LFW_DIR", os.path.join(FIXTURES, "lfw"))
        it = LFWDataSetIterator(4, image_shape=(64, 64, 3), num_classes=2,
                                shuffle=False)
        assert not it.synthetic
        ds = it.next_batch()
        assert ds.features.shape == (4, 64, 64, 3)
        assert ds.labels.shape == (4, 2)
        # two images per person, directory order
        assert np.array_equal(np.asarray(ds.labels).argmax(1), [0, 0, 1, 1])


class TestRecordReaders:
    def test_csv_classification(self, tmp_path):
        p = tmp_path / "data.csv"
        p.write_text("1.0,2.0,0\n3.0,4.0,1\n5.0,6.0,2\n7.0,8.0,0\n")
        rr = CSVRecordReader(str(p))
        it = RecordReaderDataSetIterator(rr, batch_size=3, label_index=2,
                                         num_classes=3)
        ds = it.next_batch()
        assert ds.features.shape == (3, 2)
        assert np.array_equal(ds.labels[1], [0, 1, 0])
        ds2 = it.next_batch()
        assert ds2.features.shape == (1, 2)
        assert not it.has_next()
        it.reset()
        assert it.has_next()

    def test_csv_regression_multi_target(self, tmp_path):
        p = tmp_path / "reg.csv"
        p.write_text("1,2,10,20\n3,4,30,40\n")
        rr = CSVRecordReader(str(p))
        it = RecordReaderDataSetIterator(rr, batch_size=2, label_index=2,
                                         label_index_to=3, regression=True)
        ds = it.next_batch()
        assert np.array_equal(ds.features, [[1, 2], [3, 4]])
        assert np.array_equal(ds.labels, [[10, 20], [30, 40]])

    def test_skip_lines_and_collection_reader(self, tmp_path):
        p = tmp_path / "h.csv"
        p.write_text("colA,colB,label\n1,2,0\n3,4,1\n")
        rr = CSVRecordReader(str(p), skip_lines=1)
        assert len(list(rr)) == 2
        cr = CollectionRecordReader([[1, 2, 0], [3, 4, 1]])
        it = RecordReaderDataSetIterator(cr, 2, label_index=2, num_classes=2)
        assert it.next_batch().features.shape == (2, 2)

    def test_sequence_reader_with_masks(self, tmp_path):
        # two sequences of different lengths, aligned feature/label files
        (tmp_path / "f0.csv").write_text("1,2\n3,4\n5,6\n")
        (tmp_path / "f1.csv").write_text("7,8\n")
        (tmp_path / "l0.csv").write_text("0\n1\n0\n")
        (tmp_path / "l1.csv").write_text("1\n")
        fr = CSVSequenceRecordReader(files=[tmp_path / "f0.csv",
                                            tmp_path / "f1.csv"])
        lr = CSVSequenceRecordReader(files=[tmp_path / "l0.csv",
                                            tmp_path / "l1.csv"])
        it = SequenceRecordReaderDataSetIterator(fr, lr, batch_size=2,
                                                 num_classes=2)
        ds = it.next_batch()
        assert ds.features.shape == (2, 3, 2)
        assert ds.labels.shape == (2, 3, 2)
        assert np.array_equal(ds.features_mask, [[1, 1, 1], [1, 0, 0]])
        assert np.array_equal(ds.labels_mask, ds.features_mask)
        assert np.array_equal(ds.labels[0, 1], [0, 1])

    def test_sequence_reader_label_column(self, tmp_path):
        (tmp_path / "s0.csv").write_text("1,2,0\n3,4,1\n")
        fr = CSVSequenceRecordReader(files=[tmp_path / "s0.csv"])
        it = SequenceRecordReaderDataSetIterator(fr, batch_size=1,
                                                 num_classes=2,
                                                 label_index=2)
        ds = it.next_batch()
        assert ds.features.shape == (1, 2, 2)
        assert np.array_equal(ds.labels[0, 1], [0, 1])

    def test_train_rnn_from_sequence_reader(self, tmp_path):
        """End-to-end: sequence CSVs -> masked RNN training."""
        from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                        NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.conf.layers import (GravesLSTM,
                                                       RnnOutputLayer)
        rng = np.random.default_rng(0)
        files_f, files_l = [], []
        for i in range(4):
            T = int(rng.integers(2, 6))
            f = tmp_path / f"seq{i}.csv"
            l = tmp_path / f"lab{i}.csv"
            f.write_text("\n".join(
                ",".join(f"{v:.3f}" for v in rng.random(3))
                for _ in range(T)) + "\n")
            l.write_text("\n".join(
                str(int(rng.integers(0, 2))) for _ in range(T)) + "\n")
            files_f.append(f)
            files_l.append(l)
        it = SequenceRecordReaderDataSetIterator(
            CSVSequenceRecordReader(files=files_f),
            CSVSequenceRecordReader(files=files_l),
            batch_size=4, num_classes=2)
        conf = (NeuralNetConfiguration.Builder().seed(1)
                .updater("adam").learning_rate(0.01).list()
                .layer(0, GravesLSTM(n_out=8, activation="tanh"))
                .layer(1, RnnOutputLayer(n_out=2, activation="softmax",
                                         loss_function="mcxent"))
                .set_input_type(InputType.recurrent(3))
                .build())
        net = MultiLayerNetwork(conf).init()
        net.fit(it)
        assert np.isfinite(net.score())


class _OneShotIterator:
    """Yields one (Multi)DataSet then is exhausted; reset() re-arms."""

    def __init__(self, item):
        self._item = item
        self._done = False

    def has_next(self):
        return not self._done

    def next_batch(self):
        self._done = True
        return self._item

    def reset(self):
        self._done = False


class TestMultiInputPipeline:
    @pytest.mark.slow
    def test_csv_multi_reader_async_feeds_computation_graph(self, tmp_path):
        """Round-1/2 mandate: CSV-backed RecordReaderMultiDataSetIterator
        (2 inputs, 2 outputs incl. one-hot) wrapped in
        AsyncMultiDataSetIterator feeding a 2-in/2-out ComputationGraph.fit,
        loss decreasing. reference: RecordReaderMultiDataSetIterator.java +
        AsyncMultiDataSetIterator.java + ComputationGraph.fit(MultiDataSet)."""
        from deeplearning4j_tpu import (ComputationGraph, InputType,
                                        NeuralNetConfiguration)
        from deeplearning4j_tpu.datasets import (
            AsyncMultiDataSetIterator, RecordReaderMultiDataSetIterator)
        from deeplearning4j_tpu.nn.conf.graph_vertices import MergeVertex
        from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer

        # columns: x0,x1,x2 (input A) | x3,x4 (input B) | class (3) | reg
        rng = np.random.default_rng(7)
        rows = []
        for _ in range(96):
            a = rng.random(3)
            b = rng.random(2)
            cls = int(np.argmax([a.sum(), b.sum() * 1.5, a[0] + b[1]]))
            reg = a.sum() - b.sum()
            rows.append(",".join(
                [f"{v:.4f}" for v in (*a, *b)] + [str(cls), f"{reg:.4f}"]))
        p = tmp_path / "multi.csv"
        p.write_text("\n".join(rows) + "\n")

        def make_iter():
            return AsyncMultiDataSetIterator(
                (RecordReaderMultiDataSetIterator.Builder(batch_size=16)
                 .add_reader("csv", CSVRecordReader(str(p)))
                 .add_input("csv", 0, 2)
                 .add_input("csv", 3, 4)
                 .add_output_one_hot("csv", 5, 3)
                 .add_output("csv", 6, 6)
                 .build()), queue_size=2)

        conf = (NeuralNetConfiguration.Builder().seed(3)
                .updater("adam").learning_rate(0.02)
                .graph_builder()
                .add_inputs("inA", "inB")
                .add_layer("da", DenseLayer(n_out=12, activation="relu"),
                           "inA")
                .add_layer("db", DenseLayer(n_out=12, activation="relu"),
                           "inB")
                .add_vertex("m", MergeVertex(), "da", "db")
                .add_layer("cls", OutputLayer(n_out=3, activation="softmax",
                                              loss_function="mcxent"), "m")
                .add_layer("reg", OutputLayer(n_out=1, activation="identity",
                                              loss_function="mse"), "m")
                .set_outputs("cls", "reg")
                .set_input_types(InputType.feed_forward(3),
                                 InputType.feed_forward(2))
                .build())
        net = ComputationGraph(conf).init()
        net.fit(make_iter())
        first = float(net.score())
        for _ in range(14):
            net.fit(make_iter())
        assert np.isfinite(first)
        assert float(net.score()) < first

    def test_async_multi_preserves_masks(self):
        """Masks survive the async staging path (VERDICT r2 item 4)."""
        from deeplearning4j_tpu.datasets import AsyncMultiDataSetIterator
        from deeplearning4j_tpu.datasets.dataset import MultiDataSet
        f = [np.ones((4, 5, 3), np.float32)]
        l = [np.ones((4, 5, 2), np.float32)]
        fm = [np.tril(np.ones((4, 5), np.float32))]
        lm = [np.triu(np.ones((4, 5), np.float32))]
        mds = MultiDataSet(f, l, fm, lm)
        it = AsyncMultiDataSetIterator(_OneShotIterator(mds), queue_size=2)
        staged = it.next_batch()
        assert np.array_equal(np.asarray(staged.features_masks[0]), fm[0])
        assert np.array_equal(np.asarray(staged.labels_masks[0]), lm[0])
        assert not it.has_next()

    def test_multidataset_metas_survive_wire_and_shallow_copy(self):
        """Symmetry with the DataSet paths (ADVICE r5): example_metas must
        survive MultiDataSet.shallow_copy AND the bf16-wire staging
        rebuild in AsyncMultiDataSetIterator._cast_for_wire."""
        from deeplearning4j_tpu.datasets import AsyncMultiDataSetIterator
        from deeplearning4j_tpu.datasets.dataset import MultiDataSet
        metas = [{"id": i} for i in range(4)]
        mds = MultiDataSet([np.ones((4, 3), np.float32)],
                           [np.ones((4, 2), np.float32)])
        mds.example_metas = metas
        assert mds.shallow_copy().example_metas is metas
        # bf16 wire, host-only (device staging covered above): the cast
        # rebuild used to drop metas while the DataSet path carried them
        it = AsyncMultiDataSetIterator(_OneShotIterator(mds), queue_size=2,
                                       transfer_dtype="bfloat16",
                                       cast_labels=False, device_put=False)
        out = it.next_batch()
        assert getattr(out, "example_metas", None) is metas
        # device-staged variant keeps them too (full wire path)
        it2 = AsyncMultiDataSetIterator(_OneShotIterator(mds), queue_size=2,
                                        transfer_dtype="bfloat16",
                                        cast_labels=False)
        out2 = it2.next_batch()
        assert getattr(out2, "example_metas", None) is metas


class TestUtilityIterators:
    """Reference datasets/iterator utility long tail:
    ExistingDataSetIterator, INDArray/Doubles/Floats (ArraysDataSetIterator
    here), ReconstructionDataSetIterator, MovingWindowBaseDataSetIterator,
    CombinedPreProcessor."""

    def test_existing_iterator_resets_factories_and_iterables(self):
        from deeplearning4j_tpu.datasets import ExistingDataSetIterator
        from deeplearning4j_tpu.datasets.dataset import DataSet
        batches = [DataSet(np.ones((2, 3)) * i, np.ones((2, 1)))
                   for i in range(3)]
        it = ExistingDataSetIterator(lambda: iter(batches))
        assert len(list(it)) == 3
        it.reset()
        assert it.has_next()
        assert float(it.next_batch().features[0, 0]) == 0.0

    def test_arrays_iterator_from_pairs_and_arrays(self):
        from deeplearning4j_tpu.datasets import ArraysDataSetIterator
        rng = np.random.default_rng(0)
        pairs = [(rng.random(4), rng.random(2)) for _ in range(5)]
        it = ArraysDataSetIterator(pairs, batch_size=2)
        sizes = [b.num_examples() for b in it]
        assert sizes == [2, 2, 1]
        assert it.input_columns() == 4 and it.total_outcomes() == 2
        x = rng.random((6, 3)).astype(np.float32)
        y = rng.random((6, 2)).astype(np.float32)
        it2 = ArraysDataSetIterator((x, y), batch_size=4)
        b = it2.next_batch()
        assert np.array_equal(b.features, x[:4])

    def test_reconstruction_iterator_targets_features(self):
        from deeplearning4j_tpu.datasets import (ArraysDataSetIterator,
                                                 ReconstructionDataSetIterator)
        rng = np.random.default_rng(0)
        x = rng.random((4, 3)).astype(np.float32)
        y = rng.random((4, 2)).astype(np.float32)
        it = ReconstructionDataSetIterator(
            ArraysDataSetIterator((x, y), batch_size=4))
        ds = it.next_batch()
        assert np.array_equal(ds.labels, ds.features)
        assert it.total_outcomes() == 3

    def test_moving_window_iterator(self):
        from deeplearning4j_tpu.datasets import MovingWindowDataSetIterator
        feats = np.arange(10, dtype=np.float32).reshape(10, 1)
        labs = np.arange(10, dtype=np.float32).reshape(10, 1) * 10
        it = MovingWindowDataSetIterator(feats, labs, window=3, stride=2,
                                         batch_size=2)
        b1 = it.next_batch()
        assert b1.features.shape == (2, 3, 1)
        assert np.array_equal(b1.features[0].ravel(), [0, 1, 2])
        assert np.array_equal(b1.features[1].ravel(), [2, 3, 4])
        assert float(b1.labels[0, 0]) == 20.0   # label at window end
        total = b1.num_examples() + sum(b.num_examples() for b in iter(
            lambda: it.next_batch() if it.has_next() else None, None))
        assert total == 4                        # (10-3)//2 + 1

    def test_combined_preprocessor_chains(self):
        from deeplearning4j_tpu.datasets import CombinedPreProcessor
        from deeplearning4j_tpu.datasets.dataset import DataSet

        class AddOne:
            def pre_process(self, ds):
                return DataSet(ds.features + 1, ds.labels)

        pp = (CombinedPreProcessor.Builder()
              .add_pre_processor(AddOne())
              .add_pre_processor(lambda ds: DataSet(ds.features * 2,
                                                    ds.labels))
              .build())
        out = pp.pre_process(DataSet(np.zeros((2, 2)), np.zeros((2, 1))))
        assert np.array_equal(out.features, np.full((2, 2), 2.0))


def test_existing_iterator_one_shot_generator_replays():
    """A bare generator source must not lose batches to reset() (the
    __iter__ protocol resets before iterating)."""
    from deeplearning4j_tpu.datasets import ExistingDataSetIterator
    from deeplearning4j_tpu.datasets.dataset import DataSet

    def gen():
        for i in range(3):
            yield DataSet(np.full((1, 2), float(i)), np.ones((1, 1)))

    it = ExistingDataSetIterator(gen())
    vals = [float(ds.features[0, 0]) for ds in it]
    assert vals == [0.0, 1.0, 2.0]
    # and a second full pass replays identically
    vals2 = [float(ds.features[0, 0]) for ds in it]
    assert vals2 == [0.0, 1.0, 2.0]


class TestWirePipeline:
    """r5 host->HBM wire-bytes levers (AsyncDataSetIterator transfer_dtype /
    device_transform) + DataSetIterator.set_pre_processor parity
    (reference DataSetIterator.setPreProcessor, applied on the async
    prefetch thread like AsyncDataSetIterator.java)."""

    def _data(self, n=8, f=6, c=3, seed=0):
        rng = np.random.default_rng(seed)
        x = rng.random((n, f)).astype(np.float32)
        y = np.eye(c, dtype=np.float32)[rng.integers(0, c, n)]
        return x, y

    def test_set_pre_processor_applied_by_iteration(self):
        from deeplearning4j_tpu.datasets.iterators import ArraysDataSetIterator
        x, y = self._data()
        it = ArraysDataSetIterator((x, y), batch_size=4)

        def double(ds):
            ds.features = ds.features * 2
            return ds

        it.set_pre_processor(double)
        batches = list(it)
        np.testing.assert_allclose(np.asarray(batches[0].features), x[:4] * 2)

    def test_async_applies_underlying_pre_processor_on_worker(self):
        from deeplearning4j_tpu.datasets.iterators import (
            ArraysDataSetIterator, AsyncDataSetIterator)
        from deeplearning4j_tpu.datasets.normalizers import (
            NormalizerStandardize)
        x, y = self._data(n=16)
        norm = NormalizerStandardize().fit(
            ArraysDataSetIterator((x, y), batch_size=8))
        base = ArraysDataSetIterator((x, y), batch_size=8)
        base.set_pre_processor(norm)
        got = np.concatenate([np.asarray(ds.features) for ds in
                              AsyncDataSetIterator(base, queue_size=2)])
        np.testing.assert_allclose(got, (x - norm.mean) / norm.std, rtol=2e-5)

    def test_transfer_dtype_casts_floats_only(self):
        import jax.numpy as jnp

        from deeplearning4j_tpu.datasets.iterators import (
            ArraysDataSetIterator, AsyncDataSetIterator)
        rng = np.random.default_rng(1)
        x8 = rng.integers(0, 256, (8, 5), dtype=np.uint8)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 8)]
        it = AsyncDataSetIterator(
            ArraysDataSetIterator((x8, y), batch_size=4),
            transfer_dtype="bfloat16")
        ds = it.next_batch()
        assert ds.features.dtype == np.uint8          # ints stay compact
        assert ds.labels.dtype == jnp.bfloat16        # floats shrink 2x
        # one-hot labels are exact in bf16
        np.testing.assert_array_equal(
            np.asarray(ds.labels, dtype=np.float32), y[:4])

    def test_uint8_wire_plus_device_scale_matches_host_normalize(self):
        """End-to-end: raw uint8 over the wire + ImagePreProcessingScaler
        on device == the reference-style host-side f32 transform."""
        from deeplearning4j_tpu.datasets.iterators import (
            ArraysDataSetIterator, AsyncDataSetIterator)
        from deeplearning4j_tpu.datasets.normalizers import (
            ImagePreProcessingScaler)
        rng = np.random.default_rng(2)
        x8 = rng.integers(0, 256, (8, 4, 4, 3), dtype=np.uint8)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 8)]
        scaler = ImagePreProcessingScaler()
        it = AsyncDataSetIterator(
            ArraysDataSetIterator((x8, y), batch_size=8),
            device_transform=scaler)
        dev = np.asarray(it.next_batch().features, dtype=np.float32)
        host = x8.astype(np.float32) / 255.0
        # bf16 (8-bit mantissa) rounds twice: the 1/255 constant and the
        # product — ~2^-7 relative worst case on values in [0, 1]
        np.testing.assert_allclose(dev, host, atol=2.0 ** -7)

    def test_device_apply_standardize_and_minmax_match_transform(self):
        import jax.numpy as jnp

        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.datasets.iterators import ArraysDataSetIterator
        from deeplearning4j_tpu.datasets.normalizers import (
            NormalizerMinMaxScaler, NormalizerStandardize)
        x, y = self._data(n=12)
        for norm in (NormalizerStandardize(), NormalizerMinMaxScaler(-1, 1)):
            norm.fit(ArraysDataSetIterator((x, y), batch_size=6))
            host = np.asarray(
                norm.transform(DataSet(x.copy(), y)).features)
            dev = np.asarray(norm.device_apply(jnp.asarray(x)),
                             dtype=np.float32)
            np.testing.assert_allclose(dev, host, rtol=1e-4, atol=1e-5)

    def test_num_workers_preserves_order_and_content(self):
        from deeplearning4j_tpu.datasets.iterators import (
            ArraysDataSetIterator, AsyncDataSetIterator)
        rng = np.random.default_rng(3)
        x = np.arange(64, dtype=np.float32).reshape(16, 4)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 16)]
        it = AsyncDataSetIterator(
            ArraysDataSetIterator((x, y), batch_size=2),
            queue_size=3, num_workers=4)
        feats = [np.asarray(ds.features) for ds in it]
        assert len(feats) == 8
        np.testing.assert_array_equal(np.concatenate(feats), x)
        # reset + second pass identical (pool restarts cleanly)
        feats2 = [np.asarray(ds.features) for ds in it]
        np.testing.assert_array_equal(np.concatenate(feats2), x)

    def test_num_workers_propagates_worker_error(self):
        from deeplearning4j_tpu.datasets.iterators import (
            AsyncDataSetIterator, DataSetIterator)

        class Boom(DataSetIterator):
            def __init__(self):
                self._i = 0

            def has_next(self):
                return self._i < 4

            def next_batch(self):
                self._i += 1
                if self._i == 3:
                    raise ValueError("boom")
                from deeplearning4j_tpu.datasets.dataset import DataSet
                return DataSet(np.zeros((2, 2), np.float32),
                               np.zeros((2, 2), np.float32))

            def reset(self):
                self._i = 0

        it = AsyncDataSetIterator(Boom(), num_workers=3)
        with pytest.raises((RuntimeError, ValueError)):
            while it.has_next():
                it.next_batch()

    def test_pre_processor_not_reapplied_to_cached_batches(self):
        """Cached-batch iterators hand out the same DataSet objects every
        epoch; the pre-processor must transform a shallow copy, or epoch 2
        trains on double-normalized data."""
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.datasets.iterators import (
            AsyncDataSetIterator, ListDataSetIterator, next_processed)
        from deeplearning4j_tpu.datasets.normalizers import (
            NormalizerStandardize)
        x, y = self._data(n=8)
        base = ListDataSetIterator(DataSet(x.copy(), y), batch_size=4)
        norm = NormalizerStandardize().fit(DataSet(x.copy(), y))
        base.set_pre_processor(norm)
        expect = (x - norm.mean) / norm.std
        for _pass in range(3):   # plain path: next() over 3 epochs
            base.reset()
            got = []
            while base.has_next():
                got.append(np.asarray(next_processed(base).features))
            np.testing.assert_allclose(np.concatenate(got), expect,
                                       rtol=2e-5, err_msg=f"pass {_pass}")
        for _pass in range(3):   # async path: worker-applied, 3 epochs
            it = AsyncDataSetIterator(base, queue_size=2)
            got = np.concatenate([np.asarray(ds.features) for ds in it])
            np.testing.assert_allclose(got, expect, rtol=2e-5)
        # the cached originals are untouched raw data
        np.testing.assert_array_equal(
            np.concatenate([np.asarray(b.features)
                            for b in base._batches]), x)

    def test_async_multi_wire_levers(self):
        """transfer_dtype + device_transform on the MultiDataSet path
        (ComputationGraph pipelines): uint8 inputs stay compact on the
        wire, float labels shrink to bf16, scaling happens post-stage."""
        import jax.numpy as jnp

        from deeplearning4j_tpu.datasets import AsyncMultiDataSetIterator
        from deeplearning4j_tpu.datasets.dataset import MultiDataSet
        from deeplearning4j_tpu.datasets.normalizers import (
            ImagePreProcessingScaler)
        rng = np.random.default_rng(5)
        x8a = rng.integers(0, 256, (4, 3, 3, 1), dtype=np.uint8)
        x8b = rng.integers(0, 256, (4, 2), dtype=np.uint8)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 4)]
        mds = MultiDataSet([x8a, x8b], [y])
        it = AsyncMultiDataSetIterator(
            _OneShotIterator(mds), transfer_dtype="bfloat16",
            device_transform=ImagePreProcessingScaler())
        # the wire format itself: ints pass through untouched, floats shrink
        wired = it._cast_for_wire(mds)
        assert wired.features[0].dtype == np.uint8
        assert wired.features[1].dtype == np.uint8
        assert wired.labels[0].dtype == jnp.bfloat16
        got = it.next_batch()
        assert got.labels[0].dtype == jnp.bfloat16
        for raw, dev in zip((x8a, x8b), got.features):
            np.testing.assert_allclose(
                np.asarray(dev, np.float32),
                raw.astype(np.float32) / 255.0, atol=2.0 ** -7)

    def test_bf16_model_auto_wire_is_bit_identical(self):
        """fit(plain_iterator) on a bf16 model auto-ships features as bf16
        (the step casts them to bf16 anyway) — training must be
        BIT-identical to the f32-wire path, and non-bf16 models must not
        be wire-cast at all."""
        from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                        NeuralNetConfiguration)
        from deeplearning4j_tpu.datasets.iterators import (
            ArraysDataSetIterator, AsyncDataSetIterator)
        from deeplearning4j_tpu.nn.conf.layers import (DenseLayer,
                                                       OutputLayer)
        rng = np.random.default_rng(11)
        x = rng.random((32, 6)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 32)]

        def build(dt):
            conf = (NeuralNetConfiguration.Builder().seed(5)
                    .updater("sgd").learning_rate(0.05)
                    .data_type(dt).list()
                    .layer(0, DenseLayer(n_out=8, activation="relu"))
                    .layer(1, OutputLayer(n_out=3, activation="softmax",
                                          loss_function="mcxent"))
                    .set_input_type(InputType.feed_forward(6))
                    .build())
            return MultiLayerNetwork(conf).init()

        a = build("bfloat16")
        a.fit(ArraysDataSetIterator((x, y), batch_size=16), num_epochs=4)
        b = build("bfloat16")
        b.fit(AsyncDataSetIterator(               # explicit f32 wire
            ArraysDataSetIterator((x, y), batch_size=16)), num_epochs=4)
        assert float(a._score) == float(b._score)
        np.testing.assert_array_equal(np.asarray(a.params(), np.float32),
                                      np.asarray(b.params(), np.float32))
        # float64 (gradient-check) models keep a full-precision wire:
        # plain-iterator fit (auto path) must be bit-identical to an
        # explicit no-wire async iterator — a wrongly-applied bf16 wire
        # would truncate features and break the equality
        c = build("float64")
        c.fit(ArraysDataSetIterator((x, y), batch_size=16), num_epochs=2)
        d = build("float64")
        d.fit(AsyncDataSetIterator(
            ArraysDataSetIterator((x, y), batch_size=16)), num_epochs=2)
        assert c.params().dtype == np.float64
        np.testing.assert_array_equal(np.asarray(c.params()),
                                      np.asarray(d.params()))

    def test_multiple_epochs_wrapper_applies_inner_pre_processor(self):
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.datasets.iterators import (
            ListDataSetIterator, MultipleEpochsIterator, next_processed)
        x, y = self._data(n=8)
        base = ListDataSetIterator(DataSet(x.copy(), y), batch_size=4)

        def shift(ds):
            ds.features = ds.features + 100.0
            return ds

        base.set_pre_processor(shift)
        wrapped = MultipleEpochsIterator(2, base)
        got = []
        while wrapped.has_next():
            got.append(np.asarray(next_processed(wrapped).features))
        assert len(got) == 4                     # 2 epochs x 2 batches
        np.testing.assert_allclose(np.concatenate(got[:2]), x + 100.0)
        np.testing.assert_allclose(np.concatenate(got[2:]), x + 100.0)

    def test_async_rejects_late_pre_processor_attach(self):
        from deeplearning4j_tpu.datasets.iterators import (
            ArraysDataSetIterator, AsyncDataSetIterator)
        x, y = self._data()
        it = AsyncDataSetIterator(ArraysDataSetIterator((x, y), batch_size=4))
        with pytest.raises(RuntimeError, match="underlying iterator"):
            it.set_pre_processor(lambda ds: ds)


class TestAsyncOverlap:
    """Pipeline overlap proven WITHOUT the tunnel (VERDICT r5 next #4):
    fit(AsyncDataSetIterator) on the CPU backend with a synthetic
    per-batch host delay on the feed side and a synthetic per-step delay
    on the compute side — epoch time must approach max(compute, feed),
    not their sum — plus the wire-bytes pin for the uint8 path."""

    DELAY = 0.04
    N_BATCHES = 10

    def _slow_feed(self):
        import time

        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.datasets.iterators import (
            DataSetIterator)
        rng = np.random.default_rng(0)
        x = rng.random((self.N_BATCHES * 8, 5)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[
            rng.integers(0, 3, self.N_BATCHES * 8)]
        batches = list(DataSet(x, y).batch_by(8))
        delay = self.DELAY

        class SlowIterator(DataSetIterator):
            """Simulates a host-bound source (decode/augment/disk): each
            next_batch costs `delay` seconds of host time."""

            def __init__(self):
                self._i = 0

            def has_next(self):
                return self._i < len(batches)

            def next_batch(self):
                time.sleep(delay)
                b = batches[self._i]
                self._i += 1
                return b

            def reset(self):
                self._i = 0

        return SlowIterator(), batches[0]

    def _net(self):
        from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                        NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.conf.layers import (DenseLayer,
                                                       OutputLayer)
        conf = (NeuralNetConfiguration.Builder().seed(7)
                .updater("sgd").learning_rate(0.01).list()
                .layer(0, DenseLayer(n_out=8, activation="relu"))
                .layer(1, OutputLayer(n_out=3, activation="softmax",
                                      loss_function="mcxent"))
                .set_input_type(InputType.feed_forward(5))
                .build())
        return MultiLayerNetwork(conf).init()

    def test_fit_overlaps_feed_with_compute(self):
        """With feed = compute = N*d, an overlapped pipeline finishes in
        ~max(feed, compute) = N*d; a serialized one needs the sum 2*N*d.
        The prefetch thread must hide the feed delay behind the training
        thread's per-step work (here a listener-side sleep standing in
        for step compute)."""
        import time

        it, warm = self._slow_feed()
        net = self._net()
        net.fit(warm)                       # compile off the clock
        delay = self.DELAY

        class SlowListener:
            def iteration_done(self, model, iteration):
                time.sleep(delay)           # synthetic per-step compute

        net.set_listeners(SlowListener())
        t0 = time.perf_counter()
        net.fit(it)
        elapsed = time.perf_counter() - t0
        feed = compute = self.N_BATCHES * delay
        serial = feed + compute
        assert net.conf.iteration_count >= self.N_BATCHES
        # can't beat the slower side...
        assert elapsed >= max(feed, compute) * 0.9, elapsed
        # ...but must clearly beat the serialized sum (75% margin keeps
        # this robust to a loaded CI host)
        assert elapsed < 0.75 * serial, (
            f"epoch took {elapsed:.2f}s vs serialized {serial:.2f}s — "
            f"feed is not overlapping compute")

    def test_uint8_wire_bytes_staged(self):
        """The uint8 wire carries 1 byte/element to the device — 4x fewer
        than the f32 wire the reference-style host transform would ship;
        the staged array must still BE uint8 (the device transform, when
        attached, casts on chip, not on the wire)."""
        from deeplearning4j_tpu.datasets.iterators import (
            ArraysDataSetIterator, AsyncDataSetIterator)
        rng = np.random.default_rng(1)
        x8 = rng.integers(0, 256, (8, 4, 4, 3), dtype=np.uint8)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 8)]
        it = AsyncDataSetIterator(
            ArraysDataSetIterator((x8, y), batch_size=8),
            transfer_dtype="bfloat16")
        staged = it.next_batch()
        assert staged.features.dtype == np.uint8
        assert staged.features.nbytes == x8.size          # 1 byte/elem
        # 4x fewer wire bytes than the reference-style host f32 transform
        f32_wire = x8.astype(np.float32).nbytes
        assert staged.features.nbytes * 4 == f32_wire
        # and it IS on device (the staging hop happened)
        assert hasattr(staged.features, "devices")
