"""Distributed training APIs on the virtual 8-device mesh:
ParameterAveragingTrainingMaster split/average semantics, facade, stats
timeline, async parameter-server wrapper. Mirrors reference dl4j-spark tests
run on a local-mode cluster (BaseSparkTest pattern)."""
import json

import numpy as np

from deeplearning4j_tpu import InputType, MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.parallel import (ParameterAveragingTrainingMaster,
                                         ParameterServerParallelWrapper,
                                         TpuDl4jMultiLayer)


def _net(seed=7):
    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater("adam").learning_rate(0.01).list()
            .layer(0, DenseLayer(n_out=16, activation="relu"))
            .layer(1, OutputLayer(n_out=3, activation="softmax",
                                  loss_function="mcxent"))
            .set_input_type(InputType.feed_forward(5))
            .build())
    return MultiLayerNetwork(conf).init()


def _data(n=256, seed=0):
    r = np.random.default_rng(seed)
    x = r.random((n, 5)).astype(np.float32)
    w = r.random((5, 3))
    y = np.eye(3, dtype=np.float32)[np.argmax(x @ w, axis=1)]
    return DataSet(x, y)


def test_training_master_trains_and_records_stats():
    net = _net()
    tm = (ParameterAveragingTrainingMaster.Builder(batch_size_per_worker=8)
          .workers(4).averaging_frequency(2).collect_training_stats(True)
          .build())
    ds = _data()
    s0 = net.score(ds)
    master = TpuDl4jMultiLayer(net, tm)
    master.fit(ds, num_epochs=3)
    assert net.score(ds) < s0
    phases = {e["phase"] for e in tm.stats.events}
    assert phases == {"split", "fit"}
    assert tm.stats.phase_total("fit") > 0


def test_training_master_iterator_and_eval():
    net = _net()
    tm = (ParameterAveragingTrainingMaster.Builder(batch_size_per_worker=8)
          .workers(2).averaging_frequency(2).build())
    batches = list(_data().batch_by(64))
    master = TpuDl4jMultiLayer(net, tm)
    master.fit(ListDataSetIterator(batches), num_epochs=3)
    ev = master.evaluate(list(_data(128, seed=9).batch_by(64)))
    assert ev.accuracy() > 0.5


def test_training_master_json_round_trip():
    tm = (ParameterAveragingTrainingMaster.Builder(batch_size_per_worker=32)
          .workers(4).averaging_frequency(3).build())
    d = json.loads(tm.to_json())
    tm2 = ParameterAveragingTrainingMaster.from_json(tm.to_json())
    assert tm2.batch_size == 32
    assert tm2.averaging_frequency == 3
    assert d["type"] == "ParameterAveragingTrainingMaster"


def test_stats_html_export(tmp_path):
    net = _net()
    tm = (ParameterAveragingTrainingMaster.Builder(batch_size_per_worker=8)
          .workers(2).averaging_frequency(1).collect_training_stats(True)
          .build())
    TpuDl4jMultiLayer(net, tm).fit(_data(64))
    p = tmp_path / "stats.html"
    tm.stats.export_html(str(p))
    assert "Training phases" in p.read_text()


def test_parameter_server_async_training():
    net = _net()
    ds = _data()
    s0 = net.score(ds)
    psw = (ParameterServerParallelWrapper.Builder(net)
           .workers(3).queue_size(4).build())
    psw.fit(ListDataSetIterator(list(ds.batch_by(32))), num_epochs=3)
    assert net.score(ds) < s0
    # every pushed batch was applied: 8 batches * 3 epochs
    assert net.conf.iteration_count == 24
