"""Distributed training APIs on the virtual 8-device mesh:
ParameterAveragingTrainingMaster split/average semantics, facade, stats
timeline, async parameter-server wrapper. Mirrors reference dl4j-spark tests
run on a local-mode cluster (BaseSparkTest pattern)."""
import json

import numpy as np
import pytest

from deeplearning4j_tpu import InputType, MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.parallel import (ParameterAveragingTrainingMaster,
                                         ParameterServerParallelWrapper,
                                         TpuDl4jMultiLayer)


def _net(seed=7):
    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater("adam").learning_rate(0.01).list()
            .layer(0, DenseLayer(n_out=16, activation="relu"))
            .layer(1, OutputLayer(n_out=3, activation="softmax",
                                  loss_function="mcxent"))
            .set_input_type(InputType.feed_forward(5))
            .build())
    return MultiLayerNetwork(conf).init()


def _data(n=256, seed=0):
    r = np.random.default_rng(seed)
    x = r.random((n, 5)).astype(np.float32)
    w = r.random((5, 3))
    y = np.eye(3, dtype=np.float32)[np.argmax(x @ w, axis=1)]
    return DataSet(x, y)


def test_training_master_trains_and_records_stats():
    net = _net()
    tm = (ParameterAveragingTrainingMaster.Builder(batch_size_per_worker=8)
          .workers(4).averaging_frequency(2).collect_training_stats(True)
          .rdd_training_approach("direct").build())
    ds = _data()
    s0 = net.score(ds)
    master = TpuDl4jMultiLayer(net, tm)
    master.fit(ds, num_epochs=3)
    assert net.score(ds) < s0
    phases = {e["phase"] for e in tm.stats.events}
    assert phases == {"split", "fit"}
    assert tm.stats.phase_total("fit") > 0


@pytest.mark.slow
def test_training_master_export_approach_streams_from_disk(tmp_path):
    """Reference default RDDTrainingApproach.Export: source streamed once to
    batched files, splits read from disk — the whole dataset is never
    merged into host memory (ParameterAveragingTrainingMaster.java:98-103,
    351)."""
    import os

    from deeplearning4j_tpu.parallel import training_master as tm_mod
    net = _net()
    tm = (ParameterAveragingTrainingMaster.Builder(batch_size_per_worker=8)
          .workers(4).averaging_frequency(2).collect_training_stats(True)
          .export_directory(str(tmp_path / "export")).build())
    assert tm.approach == "export"   # the default, as in the reference

    # generator-backed iterator with batches misaligned to the global batch
    # (32): would OOM if merged wholesale on a huge source. One consistent
    # labeling function across batches (slices of one dataset).
    full = _data(480, seed=3)
    slices = list(full.batch_by(24))   # 20 x 24 = 480 examples
    produced = {"n": 0}

    class GenIterator:
        def __init__(self):
            self._i = 0
        def reset(self):
            self._i = 0
        def has_next(self):
            return self._i < len(slices)
        def next_batch(self):
            ds = slices[self._i]
            self._i += 1
            produced["n"] += 1
            return ds

    orig_collect = ParameterAveragingTrainingMaster._collect_examples
    called = []
    ParameterAveragingTrainingMaster._collect_examples = staticmethod(
        lambda data: called.append(1) or orig_collect(data))
    try:
        s0 = net.score(full)
        master = TpuDl4jMultiLayer(net, tm)
        master.fit(GenIterator(), num_epochs=3)
    finally:
        ParameterAveragingTrainingMaster._collect_examples = staticmethod(
            orig_collect)
    assert not called   # never materialized in RAM
    files = sorted(os.listdir(tmp_path / "export"))
    assert len(files) == 15          # 480 examples / 32 global batch
    # exported once, reused across the 3 epochs
    assert produced["n"] == 20
    assert net.score(full) < s0
    assert {e["phase"] for e in tm.stats.events} == {"export", "fit"}


def test_training_master_export_round_trips_masks(tmp_path):
    ds = DataSet(np.ones((4, 3, 2), np.float32),
                 np.ones((4, 3, 2), np.float32),
                 np.ones((4, 3), np.float32),
                 np.zeros((4, 3), np.float32))
    p = tmp_path / "ds.npz"
    ds.save(p)
    back = DataSet.load(p)
    assert back.features_mask.shape == (4, 3)
    assert back.labels_mask.sum() == 0
    merged = DataSet.merge([ds, back])
    assert merged.features_mask.shape == (8, 3)


def test_training_master_iterator_and_eval():
    net = _net()
    tm = (ParameterAveragingTrainingMaster.Builder(batch_size_per_worker=8)
          .workers(2).averaging_frequency(2).build())
    batches = list(_data().batch_by(64))
    master = TpuDl4jMultiLayer(net, tm)
    master.fit(ListDataSetIterator(batches), num_epochs=3)
    ev = master.evaluate(list(_data(128, seed=9).batch_by(64)))
    assert ev.accuracy() > 0.5


def test_training_master_json_round_trip():
    tm = (ParameterAveragingTrainingMaster.Builder(batch_size_per_worker=32)
          .workers(4).averaging_frequency(3).build())
    d = json.loads(tm.to_json())
    tm2 = ParameterAveragingTrainingMaster.from_json(tm.to_json())
    assert tm2.batch_size == 32
    assert tm2.averaging_frequency == 3
    assert d["type"] == "ParameterAveragingTrainingMaster"


def test_stats_html_export(tmp_path):
    net = _net()
    tm = (ParameterAveragingTrainingMaster.Builder(batch_size_per_worker=8)
          .workers(2).averaging_frequency(1).collect_training_stats(True)
          .build())
    TpuDl4jMultiLayer(net, tm).fit(_data(64))
    p = tmp_path / "stats.html"
    tm.stats.export_html(str(p))
    assert "Training phases" in p.read_text()


def test_parameter_server_async_training():
    net = _net()
    ds = _data()
    s0 = net.score(ds)
    psw = (ParameterServerParallelWrapper.Builder(net)
           .workers(3).queue_size(4).build())
    psw.fit(ListDataSetIterator(list(ds.batch_by(32))), num_epochs=3)
    assert net.score(ds) < s0
    # every pushed gradient was applied: 8 batches * 3 epochs
    assert net.conf.iteration_count == 24
    stats = psw.last_stats
    assert stats["applied"] == 24
    assert stats["stale_dropped"] == 0
    # staleness was tracked for every push (values are scheduler-dependent)
    assert stats["max_staleness_seen"] >= 0


def test_gradients_accumulator_staleness_semantics():
    """Deterministic staleness check against the accumulator directly:
    gradients tagged with an old snapshot version ARE stale at apply time,
    and max_staleness bounds them."""
    import time as _time

    import jax
    from deeplearning4j_tpu.parallel.parameter_server import (
        GradientsAccumulator, _jitted_ps_fns)

    def wait_applied(acc, n, timeout=30.0):
        t0 = _time.time()
        while acc.applied_count() < n:
            if _time.time() - t0 > timeout:
                raise TimeoutError(f"applied={acc.applied_count()} never "
                                   f"reached {n}")
            _time.sleep(0.01)

    ds = _data(32)
    import jax.numpy as jnp
    batch = {"features": jnp.asarray(ds.features),
             "labels": jnp.asarray(ds.labels), "fmask": None, "lmask": None,
             "rng": jax.random.PRNGKey(0)}

    # unbounded: a version-0 gradient applied after the master moved on is
    # recorded with its true staleness
    net = _net()
    acc = GradientsAccumulator(net, queue_size=4)
    grad_fn = _jitted_ps_fns(net)[0]
    params, state, v0 = acc.snapshot_params()
    assert v0 == 0
    g, score, new_state, _ = grad_fn(params, state, batch)
    acc.push_gradients(g, score, v0, new_state)
    wait_applied(acc, 1)
    acc.push_gradients(g, score, v0, new_state)  # stale by 1 now
    wait_applied(acc, 2)
    acc.shutdown()
    st = acc.stats()
    assert st["applied"] == 2
    assert st["max_staleness_seen"] == 1
    assert net.conf.iteration_count == 2

    # bounded at 0: the same stale push is dropped, fresh ones are applied
    net2 = _net()
    acc2 = GradientsAccumulator(net2, queue_size=4, max_staleness=0)
    g2, score2, ns2, _ = grad_fn(*acc2.snapshot_params()[:2], batch)
    acc2.push_gradients(g2, score2, 0, ns2)
    wait_applied(acc2, 1)
    acc2.push_gradients(g2, score2, 0, ns2)     # stale -> dropped
    params3, state3, v3 = acc2.snapshot_params()
    g3, score3, ns3, _ = grad_fn(params3, state3, batch)
    acc2.push_gradients(g3, score3, v3, ns3)    # fresh -> applied
    wait_applied(acc2, 2)
    acc2.shutdown()
    st2 = acc2.stats()
    assert st2["applied"] == 2
    assert st2["stale_dropped"] == 1
    assert net2.conf.iteration_count == 2


def test_parameter_server_convergence_comparable_to_sync():
    ds = _data(512, seed=3)
    sync_net = _net(seed=11)
    for _ in range(3):
        sync_net.fit(ListDataSetIterator(list(ds.batch_by(32))))
    async_net = _net(seed=11)
    psw = (ParameterServerParallelWrapper.Builder(async_net)
           .workers(3).queue_size(4).build())
    psw.fit(ListDataSetIterator(list(ds.batch_by(32))), num_epochs=3)
    s_sync = sync_net.score(ds)
    s_async = async_net.score(ds)
    # async converges to the same ballpark as sync on the same data/steps
    assert s_async < 0.9  # initial score ~1.1 for 3-class mcxent
    # the gap to sync depends on gradient staleness, which depends on OS
    # thread scheduling: under CPU contention (full-suite runs) the apply
    # loop falls behind and the gap was observed up to ~0.4 on identical
    # code that scores ~0.15 unloaded — bound the ballpark, not the noise
    assert abs(s_async - s_sync) < 0.5


def test_parameter_server_updates_model_state():
    """BN running stats advance through the async PS path (worker-computed
    state is published last-writer-wins)."""
    from deeplearning4j_tpu.nn.conf.layers import BatchNormalization
    conf = (NeuralNetConfiguration.Builder().seed(7)
            .updater("sgd").learning_rate(0.05).list()
            .layer(0, DenseLayer(n_out=8, activation="identity"))
            .layer(1, BatchNormalization())
            .layer(2, OutputLayer(n_out=3, activation="softmax",
                                  loss_function="mcxent"))
            .set_input_type(InputType.feed_forward(5))
            .build())
    from deeplearning4j_tpu import MultiLayerNetwork
    net = MultiLayerNetwork(conf).init()
    init_mean = np.asarray(net._model_state[1]["mean"]).copy()
    psw = (ParameterServerParallelWrapper.Builder(net)
           .workers(2).queue_size(4).build())
    psw.fit(ListDataSetIterator(list(_data().batch_by(32))), num_epochs=2)
    new_mean = np.asarray(net._model_state[1]["mean"])
    assert not np.allclose(init_mean, new_mean)


def test_parameter_server_worker_error_propagates():
    net = _net()
    good = _data(64)
    bad = DataSet(np.zeros((8, 9), dtype=np.float32),
                  np.zeros((8, 3), dtype=np.float32))  # wrong n_in
    psw = (ParameterServerParallelWrapper.Builder(net)
           .workers(2).queue_size(2).build())
    with pytest.raises(Exception):
        psw.fit(ListDataSetIterator(list(good.batch_by(16)) + [bad]))


# ---------------------------------------------------------------------------
# TrainingHook SPI + PS hook (reference spark/api/TrainingHook.java,
# dl4j-spark-parameterserver ParameterServerTrainingHook.java)
# ---------------------------------------------------------------------------

def test_observer_hook_fires_around_splits():
    from deeplearning4j_tpu.parallel import TrainingHook

    calls = []

    class Recorder(TrainingHook):
        def pre_update(self, mb, model):
            calls.append("pre")

        def post_update(self, mb, model):
            calls.append("post")

    net = _net()
    tm = (ParameterAveragingTrainingMaster.Builder(batch_size_per_worker=8)
          .workers(4).averaging_frequency(2).rdd_training_approach("direct")
          .training_hook(Recorder()).build())
    tm.execute_training(net, _data())
    assert calls and calls.count("pre") == calls.count("post")


def test_parameter_server_hook_trains_through_master():
    """VERDICT r2 item 6: the async PS is reachable from execute_training —
    workers push gradients to the GradientsAccumulator instead of
    parameter averaging, and the model converges."""
    from deeplearning4j_tpu.parallel import ParameterServerTrainingHook

    net = _net()
    hook = ParameterServerTrainingHook(workers=3, queue_size=8,
                                       max_staleness=4)
    tm = (ParameterAveragingTrainingMaster.Builder(batch_size_per_worker=8)
          .workers(4).averaging_frequency(2).rdd_training_approach("direct")
          .training_hook(hook).build())
    ds = _data()
    s0 = net.score(ds)
    it_before = net.conf.iteration_count
    tm.execute_training(net, ds)
    # iteration counter advances exactly by gradients the accumulator
    # applied (stale-dropped pushes don't count)
    assert (net.conf.iteration_count - it_before
            == hook.last_stats["applied"])
    for _ in range(2):
        tm.execute_training(net, ds)
    assert net.score(ds) < s0
    assert hook.last_stats is not None
    assert hook.last_stats["applied"] > 0


def test_parameter_server_hook_export_path(tmp_path):
    """PS hook composes with the export (disk-streamed) approach."""
    from deeplearning4j_tpu.parallel import ParameterServerTrainingHook

    net = _net()
    hook = ParameterServerTrainingHook(workers=2)
    tm = (ParameterAveragingTrainingMaster.Builder(batch_size_per_worker=8)
          .workers(4).averaging_frequency(2)
          .rdd_training_approach("export")
          .export_directory(str(tmp_path / "exp"))
          .training_hook(hook).build())
    ds = _data()
    s0 = net.score(ds)
    tm.execute_training(net, ds)
    tm.execute_training(net, ds)
    assert net.score(ds) < s0
    assert hook.last_stats["applied"] > 0


# ---------------------------------------------------------------------------
# Cluster-side early stopping (reference SparkEarlyStoppingTrainer.java)
# ---------------------------------------------------------------------------

def test_early_stopping_over_training_master():
    from deeplearning4j_tpu.earlystopping import (
        EarlyStoppingConfiguration, MaxEpochsTerminationCondition,
        ScoreImprovementEpochTerminationCondition)
    from deeplearning4j_tpu.parallel import (MasterDataSetLossCalculator,
                                             TpuEarlyStoppingTrainer)

    net = _net()
    train = _data(256, seed=0)
    holdout = ListDataSetIterator(list(_data(96, seed=1).batch_by(32)))
    tm = (ParameterAveragingTrainingMaster.Builder(batch_size_per_worker=8)
          .workers(4).averaging_frequency(2).rdd_training_approach("direct")
          .build())
    es = (EarlyStoppingConfiguration.Builder()
          .score_calculator(MasterDataSetLossCalculator(holdout,
                                                        num_shards=4))
          .epoch_termination_conditions(
              MaxEpochsTerminationCondition(8),
              ScoreImprovementEpochTerminationCondition(2, 0.0))
          .build())
    result = TpuEarlyStoppingTrainer(es, tm, net, train).fit()
    assert result.termination_reason == "EpochTerminationCondition"
    assert result.total_epochs <= 8
    assert result.best_model is not None
    assert np.isfinite(result.best_model_score)
    # best model scores no worse than the final model on the holdout
    best = result.get_best_model()
    holdout.reset()
    assert (MasterDataSetLossCalculator(holdout, num_shards=4)
            .calculate_score(best)) <= result.score_vs_epoch[0] + 1e-6


def test_split_failure_recovery_semantics():
    """SURVEY §5.3 parity: 're-run split from last averaged params' — a
    split that fails mid-run leaves the network at the last completed
    split's averaged parameters (proven against a state-identical twin
    that runs ONLY that split), so re-running resumes training correctly
    (the reference gets this from Spark re-executing the partition
    against the re-broadcast params)."""
    import jax
    import jax.numpy as jnp

    net = _net()
    tm = (ParameterAveragingTrainingMaster.Builder(batch_size_per_worker=8)
          .workers(4).averaging_frequency(2).rdd_training_approach("direct")
          .build())
    ds = _data()
    tm.execute_training(net, ds)            # healthy run -> params P1
    p_after_split = np.asarray(net.params()).copy()
    it_after = net.conf.iteration_count
    # full state snapshot: a twin must share params, optimizer moments,
    # layer state, rng and iteration counter to reproduce the next split
    snap = (jax.tree.map(jnp.copy, net._params),
            jax.tree.map(jnp.copy, net._updater_state),
            jax.tree.map(jnp.copy, net._model_state),
            net._rng, net.conf.iteration_count)

    # inject a failure inside the next run's second split
    calls = {"n": 0}
    orig = tm._train_split

    def failing(net_, batches, hook, hook_trains):
        calls["n"] += 1
        if calls["n"] == 2:
            raise RuntimeError("injected worker failure")
        return orig(net_, batches, hook, hook_trains)

    tm._train_split = failing
    with pytest.raises(RuntimeError, match="injected"):
        tm.execute_training(net, ds)
    assert net.conf.iteration_count > it_after

    # twin from the snapshot runs ONLY the first split: the failed net
    # must sit at exactly that averaged state (nothing partially applied)
    twin = _net()
    (twin._params, twin._updater_state, twin._model_state, twin._rng,
     twin.conf.iteration_count) = snap
    tm2 = ParameterAveragingTrainingMaster.from_json(tm.to_json())
    calls2 = {"n": 0}
    orig2 = tm2._train_split

    def one_split(net_, batches, hook, hook_trains):
        calls2["n"] += 1
        if calls2["n"] == 2:
            raise RuntimeError("stop after first split")
        return orig2(net_, batches, hook, hook_trains)

    tm2._train_split = one_split
    with pytest.raises(RuntimeError, match="stop after"):
        tm2.execute_training(twin, ds)
    np.testing.assert_allclose(np.asarray(net.params()),
                               np.asarray(twin.params()), atol=1e-6)

    # recovery = re-run; training continues and loss keeps improving
    tm._train_split = orig
    s_before = net.score(ds)
    tm.execute_training(net, ds)
    assert net.score(ds) < s_before
    assert not np.allclose(np.asarray(net.params()), p_after_split)
