"""NLP long-tail: inverted index, document iterators, Porter stemming,
CJK tokenizers (VERDICT r2 missing item 6). Mirrors reference
text/invertedindex, text/documentiterator, tokenizer-preprocessor and
language-module test intents."""
import numpy as np
import pytest

from deeplearning4j_tpu.text import (AsyncLabelAwareIterator,
                                     BasicLabelAwareIterator,
                                     ChineseTokenizerFactory,
                                     CollectionSentenceIterator,
                                     FileDocumentIterator,
                                     FileLabelAwareIterator,
                                     FilenamesLabelAwareIterator,
                                     InMemoryInvertedIndex,
                                     JapaneseTokenizerFactory,
                                     KoreanTokenizerFactory,
                                     SimpleLabelAwareIterator,
                                     StemmingPreprocessor, porter_stem)


class TestInvertedIndex:
    def test_build_and_query(self):
        idx = InMemoryInvertedIndex()
        d0 = idx.append(["the", "quick", "fox"], label="animals")
        d1 = idx.append(["the", "lazy", "dog"], label="animals")
        d2 = idx.append(["quick", "quick", "sort"], label="code")
        idx.finish()
        assert idx.num_documents() == 3
        assert idx.total_words() == 9
        assert idx.documents("the") == [d0, d1]
        assert idx.documents("quick") == [d0, d2]
        assert idx.word_frequency("quick") == 3
        assert idx.positions("quick", d2) == [0, 1]
        assert idx.document(d1) == ["the", "lazy", "dog"]
        assert idx.document_with_label(d2) == (["quick", "quick", "sort"],
                                               "code")

    def test_batches_and_each_doc(self):
        idx = InMemoryInvertedIndex()
        for i in range(5):
            idx.append([f"w{i}", "x"])
        batches = list(idx.mini_batches(batch_size=2))
        assert [len(b) for b in batches] == [2, 2, 1]
        seen = []
        idx.eachDoc(lambda d: seen.append(d[0]))
        assert seen == [f"w{i}" for i in range(5)]
        assert len(list(idx.docs())) == 5

    def test_incremental_add_word_to_doc(self):
        idx = InMemoryInvertedIndex()
        idx.add_word_to_doc(0, "a")
        idx.add_word_to_doc(0, "b")
        idx.add_word_to_doc(2, "a")      # sparse doc ids auto-extend
        assert idx.document(0) == ["a", "b"]
        assert idx.document(1) == []
        assert idx.documents("a") == [0, 2]


class TestDocumentIterators:
    def test_file_document_iterator(self, tmp_path):
        (tmp_path / "b.txt").write_text("second doc")
        (tmp_path / "a.txt").write_text("first doc")
        sub = tmp_path / "sub"
        sub.mkdir()
        (sub / "c.txt").write_text("third doc")
        it = FileDocumentIterator(tmp_path)
        docs = list(it)
        assert docs == ["first doc", "second doc", "third doc"]
        it.reset()
        assert it.has_next()

    def test_file_label_aware_iterator(self, tmp_path):
        for label, texts in [("pos", ["good", "great"]),
                             ("neg", ["bad"])]:
            d = tmp_path / label
            d.mkdir()
            for i, t in enumerate(texts):
                (d / f"{i}.txt").write_text(t)
        it = FileLabelAwareIterator(tmp_path)
        docs = list(it)
        assert [(d.content, d.label) for d in docs] == [
            ("bad", "neg"), ("good", "pos"), ("great", "pos")]
        assert set(it.get_labels_source().get_labels()) == {"pos", "neg"}

    def test_filenames_and_basic_label_iterators(self, tmp_path):
        (tmp_path / "x.txt").write_text("hello")
        it = FilenamesLabelAwareIterator(tmp_path)
        d = it.next_labelled()
        assert d.content == "hello" and d.label == "x.txt"
        b = BasicLabelAwareIterator(
            CollectionSentenceIterator(["s one", "s two"]))
        labelled = list(b)
        assert [d.label for d in labelled] == ["DOC_0", "DOC_1"]

    def test_async_wrapper_preserves_order(self):
        docs = [(f"content {i}", f"L{i}") for i in range(40)]
        it = AsyncLabelAwareIterator(SimpleLabelAwareIterator(docs),
                                     buffer_size=4)
        out = [(d.content, d.label) for d in it]
        assert out == docs
        # reset restarts the stream
        it.reset()
        assert it.next_labelled().content == "content 0"


class TestStemming:
    def test_porter_classics(self):
        # canonical examples from Porter's paper
        for w, s in [("caresses", "caress"), ("ponies", "poni"),
                     ("caress", "caress"), ("cats", "cat"),
                     ("feed", "feed"), ("agreed", "agre"),
                     ("plastered", "plaster"), ("motoring", "motor"),
                     ("sing", "sing"), ("conflated", "conflat"),
                     ("troubling", "troubl"), ("sized", "size"),
                     ("hopping", "hop"), ("falling", "fall"),
                     ("happy", "happi"), ("relational", "relat"),
                     ("conditional", "condit"), ("rational", "ration"),
                     ("digitizer", "digit"), ("operator", "oper"),
                     ("feudalism", "feudal"), ("adjustable", "adjust"),
                     ("effective", "effect"), ("probate", "probat"),
                     ("rate", "rate"), ("controll", "control")]:
            assert porter_stem(w) == s, (w, porter_stem(w), s)

    def test_stemming_preprocessor_cleans_and_stems(self):
        p = StemmingPreprocessor()
        assert p.pre_process("Motoring,") == "motor"
        assert p.pre_process("'Conditional'") == "condit"


class TestCJKTokenizers:
    def test_japanese_script_segmentation(self):
        t = JapaneseTokenizerFactory().create("私は東京に住んでいます")
        toks = t.get_tokens()
        # kanji+okurigana stems stay attached, scripts split
        assert "東京に" in toks or "東京" in toks
        assert all(toks)

    def test_japanese_katakana_latin(self):
        toks = JapaneseTokenizerFactory(attach_okurigana=False).create(
            "コンピュータとAI技術").get_tokens()
        assert "コンピュータ" in toks
        assert "AI" in toks
        assert "技術" in toks

    def test_korean_particle_stripping(self):
        toks = KoreanTokenizerFactory().create("나는 학교에 갑니다").get_tokens()
        assert "학교" in toks          # 에 particle stripped
        toks_raw = KoreanTokenizerFactory(strip_particles=False).create(
            "나는 학교에 갑니다").get_tokens()
        assert "학교에" in toks_raw

    def test_chinese_per_char_han(self):
        toks = ChineseTokenizerFactory().create("我爱机器学习ML").get_tokens()
        assert toks[:6] == ["我", "爱", "机", "器", "学", "习"]
        assert "ML" in toks

    def test_word2vec_over_japanese_corpus(self):
        """End-to-end: CJK tokenizer feeding Word2Vec via the same SPI the
        reference language modules plug into."""
        from deeplearning4j_tpu.models.word2vec.word2vec import Word2Vec
        rng = np.random.default_rng(0)
        a = ["猫が好き", "犬が好き", "猫と犬"]
        b = ["車を運転", "道路と車", "運転が速い"]
        sents = [str(rng.choice(a if rng.random() < 0.5 else b))
                 for _ in range(200)]
        w2v = (Word2Vec.Builder().layer_size(16).window_size(2).seed(1)
               .negative_sample(3).epochs(3).batch_pairs(256)
               .tokenizer_factory(JapaneseTokenizerFactory())
               .iterate(CollectionSentenceIterator(sents))
               .build().fit())
        assert len(w2v.vocab) > 3
        assert np.isfinite(w2v.get_word_vector_matrix()).all()


def test_inverted_index_empty_labelled_doc():
    idx = InMemoryInvertedIndex()
    idx.add_words_to_doc(0, [], label="spam")
    assert idx.document_with_label(0) == ([], "spam")


class TestAnnotationPipeline:
    """UIMA-module equivalent (reference deeplearning4j-nlp-uima aggregate
    AnalysisEngine: sentence -> token -> stem -> pos)."""

    def test_standard_pipeline(self):
        from deeplearning4j_tpu.text import standard_pipeline
        doc = standard_pipeline().process(
            "The runners were running quickly. It was a beautiful day.")
        sents = doc.select("sentence")
        assert len(sents) == 2
        toks = doc.select("token")
        words = [t.features["text"] for t in toks]
        assert "running" in words
        assert "day." in words or "day" in words
        run = next(t for t in toks if t.features["text"] == "running")
        assert run.features["stem"] == "run"
        assert run.features["pos"] == "VBG"
        the = next(t for t in toks if t.features["text"] == "The")
        assert the.features["pos"] == "DT"
        # tokens of the first sentence only
        in_first = doc.covered(sents[0], "token")
        assert all(t.begin >= sents[0].begin and t.end <= sents[0].end
                   for t in in_first)
        assert len(in_first) == 5

    def test_custom_tokenizer_and_spans(self):
        from deeplearning4j_tpu.text import (JapaneseTokenizerFactory,
                                             AnnotationPipeline,
                                             SentenceAnnotator,
                                             TokenAnnotator)
        pipe = AnnotationPipeline(SentenceAnnotator(),
                                  TokenAnnotator(JapaneseTokenizerFactory()))
        doc = pipe.process("私は東京に住む")
        toks = doc.select("token")
        assert toks
        for t in toks:
            assert doc.text[t.begin:t.end]   # spans point into the text
