"""NLP tests: vocab/Huffman, Word2Vec (HS + negative sampling, skipgram +
CBOW) embedding quality, ParagraphVectors, GloVe, serializer round-trips,
tokenizers, vectorizers. Mirrors the reference's convergence-and-similarity
test pattern (models/paragraphvectors tests, SURVEY.md §4.7)."""
import numpy as np
import pytest

from deeplearning4j_tpu.models import Glove, ParagraphVectors, Word2Vec
from deeplearning4j_tpu.models.embeddings import serializer as WVS
from deeplearning4j_tpu.models.word2vec.vocab import (VocabCache,
                                                      build_huffman)
from deeplearning4j_tpu.text import (CollectionSentenceIterator,
                                     CommonPreprocessor,
                                     DefaultTokenizerFactory,
                                     NGramTokenizerFactory, TfidfVectorizer)
from deeplearning4j_tpu.text.vectorizers import BagOfWordsVectorizer


ANIMALS = ["cat", "dog", "pet", "fur", "tail", "paw", "claw", "kitten",
           "puppy", "whisker", "leash", "collar"]
VEHICLES = ["car", "truck", "road", "wheel", "engine", "tire", "brake",
            "gear", "fuel", "driver", "lane", "horn"]


def _toy_corpus(n_repeat=150, seed=0):
    """Two topic clusters. Words within a cluster co-occur; across clusters
    they never do. (Vocab large enough that the Huffman tree has depth —
    hierarchical softmax cannot separate a handful of words.)"""
    rng = np.random.default_rng(seed)
    seqs = []
    for _ in range(n_repeat):
        seqs.append(list(rng.choice(ANIMALS, 6, replace=False)))
        seqs.append(list(rng.choice(VEHICLES, 6, replace=False)))
    return seqs


def _check_clusters(model):
    # intra-cluster similarity must dominate inter-cluster
    intra = model.similarity("cat", "dog")
    inter = model.similarity("cat", "car")
    assert intra > inter + 0.2, (intra, inter)
    nearest = model.words_nearest("cat", top_n=4)
    assert set(nearest) <= set(ANIMALS), nearest


class TestVocab:
    def test_vocab_ordering_and_counts(self):
        v = VocabCache()
        for w in ["b", "a", "a", "c", "a", "b"]:
            v.add_token(w)
        v.finish()
        assert v.word_at_index(0) == "a"
        assert v.word_frequency("a") == 3
        assert v.index_of("zzz") == -1
        assert len(v) == 3

    def test_min_frequency_filter(self):
        v = VocabCache()
        for w in ["a"] * 5 + ["b"] * 2 + ["rare"]:
            v.add_token(w)
        v.finish(min_word_frequency=2)
        assert "rare" not in v
        assert len(v) == 2

    def test_huffman_codes_prefix_free(self):
        v = VocabCache()
        rng = np.random.default_rng(0)
        for i in range(50):
            v.add_token(f"w{i}", int(rng.integers(1, 100)))
        v.finish()
        build_huffman(v)
        codes = {tuple(w.codes) for w in v.vocab_words()}
        assert len(codes) == 50
        # prefix-free: no code is a prefix of another
        as_strings = sorted("".join(map(str, c)) for c in codes)
        for a, b in zip(as_strings, as_strings[1:]):
            assert not b.startswith(a)
        # frequent words get shorter codes
        words = v.vocab_words()
        assert len(words[0].codes) <= len(words[-1].codes)


class TestWord2Vec:
    def test_skipgram_hs(self):
        w2v = (Word2Vec.Builder().layer_size(24).window_size(3).seed(7)
               .min_word_frequency(1).learning_rate(0.05)
               .epochs(8).use_hierarchic_softmax(True).build())
        w2v.fit(_toy_corpus())
        _check_clusters(w2v)

    def test_skipgram_negative_sampling(self):
        w2v = (Word2Vec.Builder().layer_size(24).window_size(3).seed(7)
               .min_word_frequency(1).learning_rate(0.05)
               .epochs(8).negative_sample(5).build())
        w2v.fit(_toy_corpus())
        _check_clusters(w2v)

    def test_cbow(self):
        w2v = (Word2Vec.Builder().layer_size(24).window_size(3).seed(7)
               .elements_learning_algorithm("cbow")
               .learning_rate(0.05).epochs(10)
               .negative_sample(5).build())
        w2v.fit(_toy_corpus())
        _check_clusters(w2v)

    def test_sentence_iterator_path(self):
        sentences = [" ".join(s) for s in _toy_corpus(60)]
        w2v = (Word2Vec.Builder().layer_size(16).window_size(3).seed(3)
               .epochs(8).learning_rate(0.05)
               .iterate(CollectionSentenceIterator(sentences))
               .tokenizer_factory(DefaultTokenizerFactory())
               .build())
        w2v.fit()
        assert w2v.has_word("cat")
        assert w2v.get_word_vector("cat").shape == (16,)


class TestParagraphVectors:
    def test_dbow_document_clusters(self):
        corpus = _toy_corpus(60)
        docs = [(f"DOC_{i}", toks) for i, toks in enumerate(corpus)]
        pv = (ParagraphVectors.Builder().layer_size(24).seed(7)
              .learning_rate(0.05).epochs(25).negative_sample(5)
              .sequence_learning_algorithm("dbow").build())
        pv.fit(docs)
        # even-index docs are animal docs, odd are vehicle docs
        va0 = pv.get_label_vector("DOC_0")
        va2 = pv.get_label_vector("DOC_2")
        vv1 = pv.get_label_vector("DOC_1")
        from deeplearning4j_tpu.models.embeddings.model_utils import cosine_sim
        assert cosine_sim(va0, va2) > cosine_sim(va0, vv1) + 0.15

    def test_dm_and_infer(self):
        corpus = _toy_corpus(60)
        docs = [(f"DOC_{i}", toks) for i, toks in enumerate(corpus)]
        pv = (ParagraphVectors.Builder().layer_size(24).seed(7)
              .learning_rate(0.05).epochs(6).negative_sample(5)
              .sequence_learning_algorithm("dm").build())
        pv.fit(docs)
        inferred = pv.infer_vector(["cat", "dog", "pet"])
        assert inferred.shape == (24,)
        from deeplearning4j_tpu.models.embeddings.model_utils import cosine_sim
        sim_animal = cosine_sim(inferred, pv.get_word_vector("fur"))
        sim_vehicle = cosine_sim(inferred, pv.get_word_vector("wheel"))
        assert sim_animal > sim_vehicle


class TestGlove:
    def test_glove_clusters(self):
        g = (Glove.Builder().layer_size(24).window_size(3).seed(7)
             .learning_rate(0.1).epochs(25).build())
        g.fit(_toy_corpus())
        _check_clusters(g)


class TestSerializer:
    def _model(self):
        w2v = (Word2Vec.Builder().layer_size(12).window_size(3).seed(7)
               .epochs(4).learning_rate(0.05).build())
        return w2v.fit(_toy_corpus(40))

    def test_text_round_trip(self, tmp_path):
        m = self._model()
        p = str(tmp_path / "vec.txt")
        WVS.write_word2vec_text(m, p)
        m2 = WVS.read_word2vec_text(p)
        assert np.allclose(m2.get_word_vector("cat"),
                           m.get_word_vector("cat"), atol=1e-5)
        assert m2.words_nearest("cat", 2) == m.words_nearest("cat", 2)

    def test_binary_round_trip(self, tmp_path):
        m = self._model()
        p = str(tmp_path / "vec.bin")
        WVS.write_word2vec_binary(m, p)
        m2 = WVS.read_word2vec_binary(p)
        assert np.allclose(m2.get_word_vector("dog"),
                           m.get_word_vector("dog"), atol=1e-6)

    def test_full_model_round_trip(self, tmp_path):
        m = self._model()
        p = str(tmp_path / "model.zip")
        WVS.write_full_model(m, p)
        m2 = WVS.read_full_model(p)
        assert np.allclose(m2.get_word_vector("cat"),
                           m.get_word_vector("cat"))
        assert m2.vocab.word_frequency("cat") == m.vocab.word_frequency("cat")
        assert m2.lookup.syn1 is not None  # HS weights preserved

    def test_gzip_text_round_trip(self, tmp_path):
        """.gz write compresses; read sniffs the GZIP magic (reference
        loadTxtVectors behavior) — same vectors either way."""
        import gzip
        m = self._model()
        p = str(tmp_path / "vec.txt.gz")
        WVS.write_word2vec_text(m, p)
        with open(p, "rb") as fh:
            assert fh.read(2) == b"\x1f\x8b"     # really gzip on disk
        m2 = WVS.read_word2vec_text(p)
        assert np.allclose(m2.get_word_vector("cat"),
                           m.get_word_vector("cat"), atol=1e-5)

    def test_paragraph_vectors_round_trip(self, tmp_path):
        from deeplearning4j_tpu.models.paragraphvectors.paragraph_vectors \
            import ParagraphVectors
        docs = [("DOC_A", ["cat", "dog", "fur", "pet"] * 5),
                ("DOC_B", ["car", "wheel", "road", "drive"] * 5)]
        pv = (ParagraphVectors.Builder().layer_size(16).window_size(3)
              .seed(3).epochs(5).build())
        pv.fit(docs)
        p = str(tmp_path / "pv.zip")
        WVS.write_paragraph_vectors(pv, p)
        pv2 = WVS.read_paragraph_vectors(p)
        # label vectors AND the label list survive
        assert pv2.labels_source._labels == ["DOC_A", "DOC_B"]
        for lab in ("DOC_A", "DOC_B"):
            assert np.allclose(pv2.get_word_vector(lab),
                               pv.get_word_vector(lab))
        # inference works on the restored model
        v = pv2.infer_vector(["cat", "dog"])
        assert v.shape == (16,)

    def test_paragraph_vectors_negative_sampling_round_trip(self, tmp_path):
        """A negative-sampling PV restores with use_hs=False and a rebuilt
        unigram table — infer_vector must run the negative path, not
        crash on the HS default (syn1 is None for these models)."""
        from deeplearning4j_tpu.models.paragraphvectors.paragraph_vectors \
            import ParagraphVectors
        docs = [("D_A", ["cat", "dog", "fur", "pet"] * 5),
                ("D_B", ["car", "wheel", "road", "drive"] * 5),
                ("D_A", ["cat", "pet", "fur", "dog"] * 5)]   # dup label
        pv = (ParagraphVectors.Builder().layer_size(12).window_size(3)
              .seed(4).epochs(4).negative_sample(5).build())
        pv.fit(docs)
        assert pv.labels_source.get_labels() == ["D_A", "D_B"]  # dedup'd
        p = str(tmp_path / "pv_neg.zip")
        WVS.write_paragraph_vectors(pv, p)
        pv2 = WVS.read_paragraph_vectors(p)
        assert pv2.use_hs is False and pv2.negative == 5
        assert pv2.lookup.neg_table is not None
        v = pv2.infer_vector(["cat", "dog"])
        assert v.shape == (12,) and np.isfinite(v).all()

    def test_refit_replaces_label_space(self):
        from deeplearning4j_tpu.models.paragraphvectors.paragraph_vectors \
            import ParagraphVectors
        pv = (ParagraphVectors.Builder().layer_size(8).window_size(2)
              .seed(1).epochs(2).build())
        pv.fit([("X", ["a", "b", "c", "d"] * 4)])
        pv.fit([("Y", ["e", "f", "g", "h"] * 4)])
        assert pv.labels_source.get_labels() == ["Y"]   # no stale X

    def test_glove_text_export(self, tmp_path):
        g = (Glove.Builder().layer_size(12).window_size(3).seed(7)
             .learning_rate(0.1).epochs(5).build())
        g.fit(_toy_corpus(30))
        p = str(tmp_path / "glove.txt")
        WVS.write_glove_text(g, p)
        m2 = WVS.read_word2vec_text(p)
        assert np.allclose(m2.get_word_vector("cat"),
                           g.get_word_vector("cat"), atol=1e-5)


class TestTextPipeline:
    def test_default_tokenizer_and_preprocessor(self):
        tf = DefaultTokenizerFactory()
        tf.set_token_pre_processor(CommonPreprocessor())
        toks = tf.create("Hello, World! 123 foo.").get_tokens()
        assert toks == ["hello", "world", "foo"]

    def test_ngram_tokenizer(self):
        tf = NGramTokenizerFactory(min_n=1, max_n=2)
        toks = tf.create("a b c").get_tokens()
        assert toks == ["a", "b", "c", "a b", "b c"]

    def test_bow_and_tfidf(self):
        docs = ["cat dog cat", "dog truck", "truck road truck"]
        bow = BagOfWordsVectorizer()
        X = bow.fit_transform(docs)
        assert X.shape == (3, len(bow.vocab))
        ci = bow.vocab.index_of("cat")
        assert X[0, ci] == 2.0
        tfidf = TfidfVectorizer()
        Xt = tfidf.fit_transform(docs)
        # 'cat' appears in 1/3 docs -> positive idf; present only in doc 0
        assert Xt[0, tfidf.vocab.index_of("cat")] > 0
        assert Xt[1, tfidf.vocab.index_of("cat")] == 0

    def test_dataset_vectorize(self):
        docs = ["cat dog", "truck road"]
        bow = BagOfWordsVectorizer()
        bow.fit(docs)
        ds = bow.vectorize(docs, labels=["animal", "vehicle"])
        assert ds.features.shape[0] == 2
        assert ds.labels.shape == (2, 2)


class TestDistributedWord2Vec:
    """reference: dl4j-spark-nlp spark/models/embeddings/word2vec/
    Word2Vec.java:61,130 — cluster-wide embedding training. TPU-first:
    syn0/syn1 column-sharded over the mesh "model" axis; the only
    collective is the psum GSPMD inserts for the pair logits."""

    def _corpus(self, n=400, seed=0):
        rng = np.random.default_rng(seed)
        groups = [["king", "queen", "royal", "crown", "throne"],
                  ["dog", "cat", "pet", "paw", "tail"],
                  ["car", "road", "wheel", "drive", "engine"]]
        return [" ".join(rng.choice(groups[rng.integers(0, 3)], 6))
                for _ in range(n)]

    def _train(self, sents, mesh):
        from deeplearning4j_tpu.text.sentence_iterator import \
            CollectionSentenceIterator
        b = (Word2Vec.Builder().layer_size(48).window_size(3).seed(7)
             .negative_sample(5).learning_rate(0.05).epochs(2)
             .batch_pairs(1024)
             .iterate(CollectionSentenceIterator(sents)))
        if mesh is not None:
            b = b.mesh(mesh)
        return b.build().fit()

    def test_mesh_training_quality_matches_single_device(self):
        from deeplearning4j_tpu.parallel import make_mesh
        import jax
        n = min(8, len(jax.devices()))
        sents = self._corpus()
        w_d = self._train(sents, make_mesh(n_data=1, n_model=n,
                                           devices=jax.devices()[:n]))
        w_s = self._train(sents, None)
        # same-cluster words close, cross-cluster far, on the sharded model
        assert w_d.similarity("king", "queen") > \
            w_d.similarity("king", "dog") + 0.2
        # sharded math == single-device math up to reduction order
        d = np.abs(w_d.get_word_vector_matrix()
                   - w_s.get_word_vector_matrix())
        assert float(d.max()) < 1e-3

    def test_sharded_tables_actually_sharded(self):
        import jax
        from deeplearning4j_tpu.models.embeddings.learning import SkipGram
        from deeplearning4j_tpu.models.embeddings.lookup_table import \
            InMemoryLookupTable
        from deeplearning4j_tpu.models.word2vec.vocab import VocabCache
        from deeplearning4j_tpu.parallel import make_mesh
        n = min(8, len(jax.devices()))
        mesh = make_mesh(n_data=1, n_model=n, devices=jax.devices()[:n])
        vocab = VocabCache()
        for i in range(30):
            vocab.add_token(f"w{i}", count=3)
        vocab.finish()
        table = InMemoryLookupTable(vocab, vector_length=8 * n, seed=1,
                                    negative=3, use_hs=False).reset_weights()
        sg = SkipGram(batch_pairs=128)
        sg.configure(vocab, table, window=2, negative=3, use_hs=False,
                     seed=1, mesh=mesh)
        sharding = sg._syn0.sharding
        assert sharding.spec == jax.sharding.PartitionSpec(None, "model")
        sg.learn_sequence(list(range(30)) * 4, 0.025)
        sg._flush(force=True)
        # updates preserve the column sharding (donated buffers)
        assert sg._syn0.sharding.spec == \
            jax.sharding.PartitionSpec(None, "model")


class TestStaticWord2Vec:
    def test_static_mmap_queries_match_trained(self, tmp_path):
        from deeplearning4j_tpu.models.word2vec.static_word2vec import (
            StaticWord2Vec, write_static_model)
        m = (Word2Vec.Builder()
             .layer_size(32).window_size(3).negative_sample(5).epochs(3)
             .seed(7).min_word_frequency(1).learning_rate(0.05).build())
        m.fit(_toy_corpus())
        d = str(tmp_path / "static_w2v")
        write_static_model(m, d)
        sm = StaticWord2Vec(d, mmap=True)
        # vectors identical to the trained table
        np.testing.assert_allclose(sm.word_vector("cat"),
                                   m.lookup.vector("cat"), rtol=1e-6)
        # similarity + nearest queries agree with the live model
        assert abs(sm.similarity("cat", "dog") -
                   m.similarity("cat", "dog")) < 1e-5
        assert sm.words_nearest("cat", top_n=4) == \
            m.words_nearest("cat", top_n=4)
        _check_clusters(sm)
        assert sm.has_word("cat") and not sm.has_word("zeppelin")

    def test_static_is_read_only_surface(self, tmp_path):
        from deeplearning4j_tpu.models.word2vec.static_word2vec import (
            StaticWord2Vec, write_static_model)
        m = (Word2Vec.Builder()
             .layer_size(8).window_size(2).negative_sample(2).epochs(1)
             .seed(1).min_word_frequency(1).build())
        m.fit(_toy_corpus(n_repeat=5))
        d = str(tmp_path / "s")
        write_static_model(m, d)
        sm = StaticWord2Vec(d)
        assert not hasattr(sm, "fit") and not hasattr(sm, "train")
