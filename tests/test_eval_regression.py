"""RegressionEvaluation tests (reference: eval/RegressionEvaluation tests)."""
import numpy as np
import pytest

from deeplearning4j_tpu.eval.regression import RegressionEvaluation


class TestRegressionEvaluation:
    def test_perfect_prediction(self):
        ev = RegressionEvaluation(2)
        y = np.array([[1.0, 2.0], [3.0, 4.0]])
        ev.eval(y, y)
        assert ev.mean_squared_error(0) == 0.0
        assert ev.correlation_r2(1) == pytest.approx(1.0)

    def test_mse_mae(self):
        ev = RegressionEvaluation(1)
        ev.eval(np.array([[0.0], [2.0]]), np.array([[1.0], [1.0]]))
        assert ev.mean_squared_error(0) == pytest.approx(1.0)
        assert ev.mean_absolute_error(0) == pytest.approx(1.0)
        assert ev.root_mean_squared_error(0) == pytest.approx(1.0)

    def test_merge_equals_joint(self):
        rng = np.random.default_rng(0)
        y = rng.normal(size=(20, 3)); p = y + rng.normal(0, 0.1, (20, 3))
        joint = RegressionEvaluation(3).eval(y, p)
        a = RegressionEvaluation(3).eval(y[:10], p[:10])
        b = RegressionEvaluation(3).eval(y[10:], p[10:])
        a.merge(b)
        for c in range(3):
            assert a.mean_squared_error(c) == pytest.approx(joint.mean_squared_error(c))
            assert a.correlation_r2(c) == pytest.approx(joint.correlation_r2(c))
