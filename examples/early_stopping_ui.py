"""Early stopping + training UI: StatsListener streams per-iteration stats
into a storage backend served by the web UI while an early-stopping
trainer drives the run and keeps the best checkpoint.

(reference pattern: dl4j-examples EarlyStoppingMNIST + UIExample)
"""
import _common  # noqa: F401

import tempfile

import numpy as np

from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                NeuralNetConfiguration)
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.earlystopping.early_stopping import (
    DataSetLossCalculator, EarlyStoppingConfiguration,
    EarlyStoppingTrainer, LocalFileModelSaver,
    MaxEpochsTerminationCondition, ScoreImprovementEpochTerminationCondition)
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.ui import InMemoryStatsStorage, StatsListener, UIServer

conf = (NeuralNetConfiguration.Builder()
        .seed(7).updater("adam").learning_rate(5e-3)
        .list()
        .layer(0, DenseLayer(n_out=32, activation="relu"))
        .layer(1, OutputLayer(n_out=3, activation="softmax",
                              loss_function="mcxent"))
        .set_input_type(InputType.feed_forward(4))
        .build())
net = MultiLayerNetwork(conf).init()

storage = InMemoryStatsStorage()
net.set_listeners(StatsListener(storage, session_id="example"))
server = UIServer(port=0).attach(storage)
print(f"UI live at http://127.0.0.1:{server.port} "
      f"(overview/model/histograms/flow/system)")

rng = np.random.default_rng(0)
centers = rng.normal(0, 3, (3, 4))
c = rng.integers(0, 3, 256)
x = (centers[c] + rng.normal(0, 0.5, (256, 4))).astype(np.float32)
y = np.eye(3, dtype=np.float32)[c]

savedir = tempfile.mkdtemp()
es = (EarlyStoppingConfiguration.Builder()
      .model_saver(LocalFileModelSaver(savedir))
      .score_calculator(DataSetLossCalculator(
          ListDataSetIterator(DataSet(x, y), 128)))
      .epoch_termination_conditions(
          MaxEpochsTerminationCondition(30),
          ScoreImprovementEpochTerminationCondition(5))
      .build())
result = EarlyStoppingTrainer(es, net,
                              ListDataSetIterator(DataSet(x, y), 64)).fit()
print(result)
print("updates collected by the UI:",
      len(storage.get_all_updates("example")))
server.stop()
