"""A residual network as a ComputationGraph DAG: multi-branch vertices
(ElementWiseVertex add), bias-free convs before BN, one fused bf16
training step. The zoo `resnet50()` is the full benchmark model built from
the same pieces.

(reference pattern: ComputationGraph residual configuration)
"""
import _common  # noqa: F401

import numpy as np

from deeplearning4j_tpu import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf.graph_vertices import ElementWiseVertex
from deeplearning4j_tpu.nn.conf.layers import (ActivationLayer,
                                               BatchNormalization,
                                               ConvolutionLayer, DenseLayer,
                                               GlobalPoolingLayer,
                                               OutputLayer)
from deeplearning4j_tpu.nn.graph import ComputationGraph

gb = (NeuralNetConfiguration.Builder()
      .seed(11).updater("adam").learning_rate(2e-3)
      .graph_builder()
      .add_inputs("in")
      .add_layer("conv1", ConvolutionLayer(n_out=16, kernel_size=(3, 3),
                                           padding=(1, 1), has_bias=False),
                 "in")
      .add_layer("bn1", BatchNormalization(), "conv1")
      .add_layer("relu1", ActivationLayer(activation="relu"), "bn1")
      # residual branch
      .add_layer("conv2", ConvolutionLayer(n_out=16, kernel_size=(3, 3),
                                           padding=(1, 1), has_bias=False),
                 "relu1")
      .add_layer("bn2", BatchNormalization(), "conv2")
      .add_vertex("add", ElementWiseVertex(op="add"), "bn2", "relu1")
      .add_layer("relu2", ActivationLayer(activation="relu"), "add")
      .add_layer("pool", GlobalPoolingLayer(pooling_type="AVG"), "relu2")
      .add_layer("out", OutputLayer(n_out=4, activation="softmax",
                                    loss_function="mcxent"), "pool")
      .set_outputs("out")
      .set_input_types(InputType.convolutional(16, 16, 3))
      .build())
net = ComputationGraph(gb).init()

rng = np.random.default_rng(0)
x = rng.random((32, 16, 16, 3)).astype(np.float32)
y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 32)]
ds = DataSet(x, y)
s0 = float(net.score(ds))
for _ in range(15):
    net.fit(ds)
print(f"score {s0:.3f} -> {float(net.score(ds)):.3f}")
