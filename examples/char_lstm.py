"""Character LSTM: GravesLSTM + RnnOutputLayer trained with truncated BPTT
on a toy shift task, then streamed generation via `rnn_time_step`.

(reference pattern: dl4j-examples GravesLSTMCharModellingExample)
"""
import _common  # noqa: F401

import numpy as np

from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                NeuralNetConfiguration)
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf.layers import (GravesLSTM, RnnOutputLayer)

V, B, T = 12, 16, 32
conf = (NeuralNetConfiguration.Builder()
        .seed(12).updater("adam").learning_rate(5e-3)
        .list()
        .layer(0, GravesLSTM(n_out=48, activation="tanh"))
        .layer(1, RnnOutputLayer(n_out=V, activation="softmax",
                                 loss_function="mcxent"))
        .set_input_type(InputType.recurrent(V))
        .backprop_type("tbptt").t_bptt_forward_length(16)
        .build())
net = MultiLayerNetwork(conf).init()

rng = np.random.default_rng(0)
ids = rng.integers(0, V, (B, T))
x = np.eye(V, dtype=np.float32)[ids]          # [B, T, V] one-hot
y = np.eye(V, dtype=np.float32)[(ids + 1) % V]
ds = DataSet(x, y)
for epoch in range(60):
    net.fit(ds)
print("final score:", float(net.score(ds)))

# streamed generation, one step at a time (state carried inside)
net.rnn_clear_previous_state()
step = np.eye(V, dtype=np.float32)[[3]][:, None, :]   # [1, 1, V]
seq = [3]
for _ in range(8):
    out = net.rnn_time_step(step)                     # [1, 1, V]
    nxt = int(out[0, -1].argmax())
    seq.append(nxt)
    step = np.eye(V, dtype=np.float32)[[nxt]][:, None, :]
print("greedy rollout from 3:", seq)
