"""LeNet CNN from the model zoo: conv/pool layers with InputType shape
inference, bf16 compute.

(reference pattern: dl4j-examples LenetMnistExample)
"""
import _common  # noqa: F401

import numpy as np

from deeplearning4j_tpu.datasets.mnist import MnistDataSetIterator
from deeplearning4j_tpu.models.zoo.lenet import lenet

net = lenet(data_type="bfloat16")
train = MnistDataSetIterator(128, train=True)
print("data source:", "synthetic stand-in" if train.synthetic else "MNIST")
net.fit(train, num_epochs=1)
ev = net.evaluate(MnistDataSetIterator(128, train=False))
print("accuracy:", round(ev.accuracy(), 3))
