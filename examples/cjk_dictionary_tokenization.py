"""CJK dictionary ingestion: compile a mecab-format dictionary (token
CSVs + matrix.def + char.def + unk.def) and a Kuromoji-format user
dictionary into the Japanese Viterbi lattice, and load a KoreanText-layout
wordlist directory into the Korean analyzer.

reference: com/atilika/kuromoji/ipadic/compile/DictionaryCompiler.java,
dict/UserDictionary.java; deeplearning4j-nlp-korean KoreanTokenizer.java.
"""
import _common  # noqa: F401

import os

from deeplearning4j_tpu.text import (JapaneseLatticeTokenizer,
                                     JapaneseLatticeTokenizerFactory,
                                     KoreanMorphTokenizer,
                                     KoreanMorphTokenizerFactory,
                                     compile_dictionary, load_dictionary)

FIX = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tests", "fixtures")

# --- Japanese: mecab-format dictionary + user dictionary ------------------
ja = os.path.join(FIX, "ja_dict")
fac = JapaneseLatticeTokenizerFactory(
    dict_path=ja, user_dict_path=os.path.join(ja, "userdict.txt"))
toks = fac.create("関西国際空港に行った")
got = toks.get_tokens()                 # consumes, reference semantics
print("user-dict segmentation:", "|".join(got), toks.pos_tags)
assert got == ["関西", "国際", "空港", "に", "行った"]

# the dictionary's costs pick 東京都 over 東京+都; the bundled lexicon
# (no dict_path) segments by ITS costs — ingestion really changes behavior
withdict = fac.create("東京都に住む").get_tokens()
builtin = JapaneseLatticeTokenizer("東京都に住む").get_tokens()
print("fixture dict:", "|".join(withdict), " builtin:", "|".join(builtin))
assert withdict == ["東京都", "に", "住む"] and withdict != builtin

# unknown words still segment via unk.def categories (katakana grouped)
unk = fac.create("コンピュータに住む").get_tokens()
assert unk == ["コンピュータ", "に", "住む"]

# compiled-artifact round trip (the DictionaryCompiler output role)
import tempfile
dic = compile_dictionary(ja)
with tempfile.TemporaryDirectory() as td:
    p = os.path.join(td, "compiled.json")
    dic.save_compiled(p)
    from deeplearning4j_tpu.text import MecabDictionary
    dic2 = MecabDictionary.load_compiled(p)
    assert (JapaneseLatticeTokenizer("東京都に住む",
                                     dictionary=dic2).get_tokens()
            == ["東京都", "に", "住む"])

# --- Korean: wordlist directory + runtime extension -----------------------
ko = load_dictionary(os.path.join(FIX, "ko_dict"))
kfac = KoreanMorphTokenizerFactory(dictionary=ko)
ko_got = kfac.create("바다는 넓다").get_tokens()
print("korean:", ko_got)
assert ko_got == ["바다", "는", "넓", "다"]
assert KoreanMorphTokenizer("바다").get_tokens() == ["바", "다"]  # heuristic
ko.add_words("noun", ["도자기"])                 # addNounsToDictionary role
assert kfac.create("도자기").get_tokens() == ["도자기"]

print(True)
