"""Pipeline parallelism: a decoder-only transformer LM with its uniform
blocks sharded one-per-device over the "pipe" mesh axis (GPipe schedule,
microbatches rotating over ICI, backward by autodiff) combined with data
parallelism on a second axis.

No reference equivalent (SURVEY.md §2.5: PP absent) — TPU-first extension.
"""
import _common  # noqa: F401

import numpy as np

from deeplearning4j_tpu.models.zoo.transformer import (embed_fn, init_lm,
                                                       lm_loss,
                                                       make_block_fn)
from deeplearning4j_tpu.parallel import (PipelineParallel,
                                         make_pipeline_mesh)

mesh = make_pipeline_mesh(n_pipe=4, n_data=2)   # 8 devices: dp=2 x pp=4
aux, blocks = init_lm(vocab_size=11, d_model=32, n_heads=4, n_layers=4,
                      max_len=16, seed=7)
pp = PipelineParallel(make_block_fn(4), blocks, mesh, loss_fn=lm_loss,
                      aux_params=aux, pre_fn=embed_fn, n_micro=4,
                      data_axis="data", learning_rate=0.2, momentum=0.9)

rng = np.random.default_rng(0)
x = rng.integers(0, 11, (32, 16)).astype(np.int32)
y = (x + 1) % 11                                # learn the +1 shift task
first = pp.fit_batch(x, y)
for step in range(40):
    last = pp.fit_batch(x, y)
print(f"loss {first:.3f} -> {last:.4f} "
      f"(stage params sharded: {pp.stacked['attn']['wqkv'].sharding.spec})")
