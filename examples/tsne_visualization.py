"""t-SNE at two scales: the exact dense kernel (small N, one jitted
fori_loop on the accelerator) and Barnes-Hut (large N — C++ quadtree
repulsion + sparse kNN attraction; the kNN search and every point's
perplexity bisection run vectorized in JAX).

reference: plot/Tsne.java + plot/BarnesHutTsne.java + clustering/sptree.
"""
import _common  # noqa: F401

import numpy as np

from deeplearning4j_tpu.plot import Tsne
from deeplearning4j_tpu.plot.tsne import BarnesHutTsne

rng = np.random.default_rng(0)
centers = rng.standard_normal((5, 16)) * 8.0
labels = np.repeat(np.arange(5), 400)
x = (centers[labels] + rng.standard_normal((2000, 16))).astype(np.float32)

# auto: dense exact kernel below ~4k points, Barnes-Hut above
emb = (Tsne.Builder().set_max_iter(250).perplexity(25).theta(0.5)
       .seed(3).build().fit(x))

# force the Barnes-Hut path (any N, 2-D)
emb_bh = BarnesHutTsne(perplexity=25, max_iter=250, seed=3).fit(x)

for name, e in (("auto", emb), ("barnes_hut", emb_bh)):
    cents = np.stack([e[labels == i].mean(0) for i in range(5)])
    intra = np.mean([np.linalg.norm(e[labels == i] - cents[i], axis=1).mean()
                     for i in range(5)])
    inter = np.mean([np.linalg.norm(cents[i] - cents[j])
                     for i in range(5) for j in range(i + 1, 5)])
    print(f"{name}: embedding {e.shape}, cluster separation "
          f"inter/intra = {inter / intra:.2f} (separated: "
          f"{bool(inter / intra > 2)})")
print(True)
