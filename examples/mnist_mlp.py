"""MNIST MLP: builder DSL -> MultiLayerNetwork -> fit -> evaluate.

(reference pattern: dl4j-examples MLPMnistSingleLayerExample)
"""
import _common  # noqa: F401

from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                NeuralNetConfiguration)
from deeplearning4j_tpu.datasets.mnist import MnistDataSetIterator
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer

conf = (NeuralNetConfiguration.Builder()
        .seed(123)
        .updater("adam").learning_rate(1e-3)
        .list()
        .layer(0, DenseLayer(n_out=256, activation="relu"))
        .layer(1, OutputLayer(n_out=10, activation="softmax",
                              loss_function="mcxent"))
        .set_input_type(InputType.feed_forward(784))
        .build())

net = MultiLayerNetwork(conf).init()
train = MnistDataSetIterator(128, train=True)
print("data source:", "synthetic stand-in" if train.synthetic else "MNIST")
net.fit(train, num_epochs=2)

ev = net.evaluate(MnistDataSetIterator(128, train=False))
print(ev.stats())
