"""dp x tp x pp in ONE program: Megatron tensor-parallel transformer
blocks (attention heads + MLP hidden sharded over "model", two psums per
block) running INSIDE the GPipe rotation over "pipe", with the batch
sharded over "data" — the scaling-book 3-axis mesh recipe, all in a
single shard_map/jit program.

No reference equivalent (its only distribution axis is data parallelism).
"""
import _common  # noqa: F401

import numpy as np

from deeplearning4j_tpu.models.zoo.transformer import (
    embed_fn, init_lm, init_tp_block, lm_loss, make_tp_block_fn,
    tp_block_specs)
from deeplearning4j_tpu.parallel.pipeline import (PipelineParallel,
                                                  make_pipeline_mesh)

# 8 devices: data=2 x model=2 x pipe=2
mesh = make_pipeline_mesh(n_pipe=2, n_data=2, n_model=2)
print("mesh axes:", mesh.axis_names, "shape:", dict(mesh.shape))

D, HEADS = 32, 4
rng = __import__("jax").random.PRNGKey(3)
blocks = [init_tp_block(__import__("jax").random.fold_in(rng, i), D,
                        HEADS, 64) for i in range(2)]
aux, _ = init_lm(11, d_model=D, n_heads=HEADS, n_layers=1, max_len=16,
                 seed=5)
pp = PipelineParallel(
    make_tp_block_fn(HEADS // 2, "model"), blocks, mesh, loss_fn=lm_loss,
    aux_params=aux, pre_fn=embed_fn, n_micro=2, data_axis="data",
    learning_rate=0.5, momentum=0.9,
    param_specs=tp_block_specs("pipe", "model"))

# weights really live sharded on BOTH non-data axes
wqkv = pp.stacked["attn"]["wqkv"]
print("wqkv sharding:", tuple(wqkv.sharding.spec))

r = np.random.default_rng(0)
x = r.integers(0, 11, (16, 16)).astype(np.int32)
y = (x + 1) % 11
first = pp.fit_batch(x, y)
for _ in range(30):
    last = pp.fit_batch(x, y)
print(f"loss {first:.3f} -> {last:.3f}")
print(bool(last < first * 0.6
           and tuple(wqkv.sharding.spec)[:2] == ("pipe", "model")))
