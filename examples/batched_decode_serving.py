"""Batched KV-cache text generation: the WHOLE generation (prompt prefill
scan + greedy decode scan with on-device argmax) is one jitted program, so
the host touches the device once per call — the TPU serving pattern (on a
remote-attached chip the per-token host round trip of naive decoding IS
the bottleneck).

reference parity: MultiLayerNetwork.rnnTimeStep (O(1)-state streaming
inference), attention era.
"""
import _common  # noqa: F401

import numpy as np

from deeplearning4j_tpu.models.zoo.transformer import TransformerLM

V = 11
lm = TransformerLM(V, d_model=32, n_heads=4, n_layers=2, max_len=32,
                   learning_rate=0.2, momentum=0.9)

# teach the toy task: next token = current + 1 (mod V)
rng = np.random.default_rng(0)
x = rng.integers(0, V, (16, 16)).astype(np.int32)
for _ in range(120):
    loss = lm.fit_batch(x, (x + 1) % V)

prompts = np.array([[2, 3, 4], [7, 8, 9], [0, 1, 2], [5, 6, 7]], np.int32)
out = lm.generate_batch(prompts, max_new_tokens=6)
print("prompts:", prompts.tolist())
print("continuations:", out[:, 3:].tolist())

# greedy outputs are token-identical to the per-token cache decode
row0 = lm.generate(prompts[0], max_new_tokens=6, use_cache=True)
print("batch row 0 == per-token decode:", list(out[0]) == row0)
print(list(out[0]) == row0 and float(loss) < 1.0)
