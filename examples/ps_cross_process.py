"""Cross-process asynchronous parameter server: a master process owns the
accumulator behind a TCP PSServer; worker processes pull version-tagged
snapshots and push gradients through PSClient — the reference's
Aeron-backed ParameterServerParallelWrapper topology
(ParameterServerParallelWrapper.java:159-160) over a socket transport.

This example spawns ONE real worker subprocess against an in-process
server (the 2-process convergence test in tests/test_ps_transport.py runs
the full two-worker topology).
"""
import _common  # noqa: F401

import os
import subprocess
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tests"))

from ps_remote_server import build_data, build_net  # noqa: E402

from deeplearning4j_tpu.parallel import PSServer  # noqa: E402

net = build_net()
ds = build_data()
s0 = float(net.score(ds))
srv = PSServer(net, queue_size=4, n_workers=1)

env = {k: v for k, v in os.environ.items()
       if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
env["PYTHONPATH"] = REPO + os.pathsep + os.path.join(REPO, "tests")
worker = subprocess.run(
    [sys.executable, os.path.join(REPO, "tests", "ps_remote_worker.py"),
     "0", "1", str(srv.port)],
    capture_output=True, text=True, env=env, timeout=240)
assert worker.returncode == 0, worker.stdout + worker.stderr
stats = srv.wait(timeout=60)

s1 = float(net.score(ds))
print(f"score {s0:.4f} -> {s1:.4f}; applied={stats['applied']} "
      f"stale_dropped={stats['stale_dropped']} "
      f"max_staleness={stats['max_staleness_seen']}")
assert s1 < s0 and stats["applied"] + stats["stale_dropped"] == 24
print(True)
