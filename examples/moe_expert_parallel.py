"""Mixture-of-Experts with expert parallelism: Switch-style top-1 routing,
one expert FFN per device over an ("expert",) mesh, tokens exchanged with
`lax.all_to_all` over ICI.

No reference equivalent (SURVEY.md §2.5: EP absent) — TPU-first extension.
"""
import _common  # noqa: F401

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.parallel import (init_moe, make_expert_mesh,
                                         moe_mlp_dense, moe_mlp_sharded,
                                         shard_moe_params)

D, E, F, B = 16, 8, 64, 64
mesh = make_expert_mesh(E)
params = init_moe(jax.random.PRNGKey(0), D, E, F)
sharded = shard_moe_params(params, mesh)
x = jnp.asarray(np.random.default_rng(0).standard_normal((B, D)),
                jnp.float32)

apply_ep = jax.jit(moe_mlp_sharded(mesh))
y_ep, aux = apply_ep(sharded, x)
y_ref, _ = moe_mlp_dense(params, x)
print("expert-parallel == dense reference:",
      bool(jnp.allclose(y_ep, y_ref, atol=1e-5)))
print("load-balance aux loss:", float(aux))
print("expert weights sharding:", sharded["w1"].sharding.spec)

# top-2 combine (GShard/Mixtral): same all_to_all dispatch, each token
# summing two gated expert returns
y2, _ = jax.jit(moe_mlp_sharded(mesh, k=2))(sharded, x)
y2_ref, _ = moe_mlp_dense(params, x, k=2)
print("top-2 expert-parallel == dense:",
      bool(jnp.allclose(y2, y2_ref, atol=1e-5)))
