"""Long-context attention: the sequence axis sharded over the mesh (ring
attention — K/V blocks rotate over ICI while an online softmax folds each
block), with the Pallas flash kernel as each device's block compute.

No reference equivalent (the 2016 stack predates attention; its only
long-sequence tool is truncated BPTT) — TPU-first extension.
"""
import _common  # noqa: F401

import jax
import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh

from deeplearning4j_tpu.parallel.ring_attention import (
    blockwise_attention, ring_self_attention)

mesh = Mesh(np.array(jax.devices()), ("seq",))
rng = np.random.default_rng(1)
B, T, H, D = 2, 128, 4, 16                      # T shards over 8 devices
q = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)

ring = ring_self_attention(q, q, q, mesh, axis="seq", causal=True)
flash = ring_self_attention(q, q, q, mesh, axis="seq", causal=True,
                            use_flash=True)
full = blockwise_attention(q, q, q, causal=True)
print("ring == full:", bool(jnp.allclose(ring, full, atol=1e-4)),
      " ring+flash == full:", bool(jnp.allclose(flash, full, atol=1e-4)))

# TRAINING on the flash path: gradients come from the fused ring backward
# (a reverse ring over the Pallas dQ/dK+dV passes — no score panel is
# ever materialized, forward or backward)
g_flash = jax.grad(lambda q: jnp.mean(ring_self_attention(
    q, q, q, mesh, axis="seq", causal=True, use_flash=True) ** 2))(q)
g_full = jax.grad(lambda q: jnp.mean(blockwise_attention(
    q, q, q, causal=True) ** 2))(q)
grads_match = bool(jnp.allclose(g_flash, g_full, atol=1e-4))
print("fused ring backward grads == single-device grads:", grads_match)
print(grads_match)
