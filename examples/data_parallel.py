"""ParallelWrapper: ONE jitted training step partitioned over the device
mesh — data parallelism, optional tensor parallelism and ZeRO-1 sharded
optimizer state. On a TPU pod slice the same code scales over ICI.

(reference pattern: dl4j-examples ParallelWrapper MultiGpuLenetMnistExample)
"""
import _common  # noqa: F401

import numpy as np

from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                NeuralNetConfiguration)
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.parallel import ParallelWrapper

conf = (NeuralNetConfiguration.Builder()
        .seed(42).updater("adam").learning_rate(5e-3)
        .list()
        .layer(0, DenseLayer(n_out=64, activation="relu"))
        .layer(1, OutputLayer(n_out=3, activation="softmax",
                              loss_function="mcxent"))
        .set_input_type(InputType.feed_forward(4))
        .build())
net = MultiLayerNetwork(conf).init()

rng = np.random.default_rng(0)
centers = rng.normal(0, 3, (3, 4))
c = rng.integers(0, 3, 512)
x = (centers[c] + rng.normal(0, 0.5, (512, 4))).astype(np.float32)
y = np.eye(3, dtype=np.float32)[c]

pw = (ParallelWrapper.Builder(net)
      .workers(8)                   # devices on the "data" mesh axis
      .averaging_frequency(1)       # per-step gradient allreduce (GSPMD)
      .sharded_updater_state(True)  # ZeRO-1: Adam moments sharded
      .build())
print("before:", float(net.score(DataSet(x, y))))
pw.fit(ListDataSetIterator(DataSet(x, y), 128), num_epochs=20)
print("after: ", float(net.score(DataSet(x, y))))
m = net._updater_state[0]["W"]["m"]
print("Adam moment sharding:", m.sharding.spec)
