"""Word2Vec: fit on a toy two-topic corpus, query nearest words, then save
and serve the table read-only via the memory-mapped StaticWord2Vec.

(reference pattern: dl4j-examples Word2VecRawTextExample)
"""
import _common  # noqa: F401

import tempfile

import numpy as np

from deeplearning4j_tpu.models import Word2Vec
from deeplearning4j_tpu.models.word2vec import (StaticWord2Vec,
                                                write_static_model)

ANIMALS = ["cat", "dog", "pet", "fur", "tail", "paw", "claw", "kitten",
           "puppy", "whisker", "leash", "collar"]
VEHICLES = ["car", "truck", "road", "wheel", "engine", "tire", "brake",
            "gear", "fuel", "driver", "lane", "horn"]
rng = np.random.default_rng(0)
corpus = []
for _ in range(150):
    corpus.append(list(rng.choice(ANIMALS, 6, replace=False)))
    corpus.append(list(rng.choice(VEHICLES, 6, replace=False)))

w2v = (Word2Vec.Builder()
       .layer_size(32).window_size(3).negative_sample(5)
       .learning_rate(0.05).epochs(5).min_word_frequency(1).seed(7)
       .build())
w2v.fit(corpus)
print("nearest(cat):", w2v.words_nearest("cat", top_n=5))
print("sim(cat, dog) =", round(w2v.similarity("cat", "dog"), 3),
      " sim(cat, car) =", round(w2v.similarity("cat", "car"), 3))

d = tempfile.mkdtemp()
write_static_model(w2v, d)
static = StaticWord2Vec(d, mmap=True)
print("static nearest(engine):", static.words_nearest("engine", top_n=5))
