"""TPU-first input pipeline: raw uint8 over the wire, normalize on device.

The reference feeds fit() float arrays that a DataNormalization already
transformed on the host (ImagePreProcessingScaler via DataVec) — so every
batch crosses host->HBM as float32. On TPU the affine scale fuses into the
first convolution for free, so the wire can carry the raw uint8 pixels
(4x fewer bytes) and bf16 labels (2x fewer) while AsyncDataSetIterator's
prefetch thread applies the normalizer ON DEVICE, overlapped with the
training step. Measured on a remote-attached v5e: 22.5 -> 177 img/s on
ResNet-50 fit() (see PERF.md round 5).

reference: datasets/iterator/AsyncDataSetIterator.java:75-76 (device-pinned
prefetch), ImagePreProcessingScaler.java (host-side transform replaced by
Normalizer.device_apply here).
"""
import _common  # noqa: F401

import numpy as np

from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                NeuralNetConfiguration)
from deeplearning4j_tpu.datasets.iterators import (ArraysDataSetIterator,
                                                   AsyncDataSetIterator)
from deeplearning4j_tpu.datasets.normalizers import ImagePreProcessingScaler
from deeplearning4j_tpu.nn.conf.layers import (ConvolutionLayer, DenseLayer,
                                               OutputLayer, SubsamplingLayer)

rng = np.random.default_rng(0)

# raw uint8 images, as an ImageRecordReader would yield them
x8 = rng.integers(0, 256, (256, 28, 28, 1), dtype=np.uint8)
y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 256)]

def build_net():
    # fresh configuration per network: conf carries iteration/epoch
    # counters, so sharing one instance would skew LR schedules between
    # the two arms
    conf = (NeuralNetConfiguration.Builder()
            .seed(123)
            .updater("adam").learning_rate(1e-3)
            .data_type("bfloat16")
            .list()
            .layer(0, ConvolutionLayer(n_out=8, kernel_size=(3, 3),
                                       activation="relu"))
            .layer(1, SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
            .layer(2, DenseLayer(n_out=64, activation="relu"))
            .layer(3, OutputLayer(n_out=10, activation="softmax",
                                  loss_function="mcxent"))
            .set_input_type(InputType.convolutional(28, 28, 1))
            .build())
    return MultiLayerNetwork(conf).init()


net = build_net()

scaler = ImagePreProcessingScaler()          # [0, 255] -> [0, 1]
base = ArraysDataSetIterator((x8, y), batch_size=64)
it = AsyncDataSetIterator(
    base,
    queue_size=4,
    transfer_dtype="bfloat16",     # float arrays (labels) ship as bf16
    # uint8 pixels scale on device; pass the model dtype so the staged
    # batch is written once in bf16 (safe: the step casts to bf16 anyway)
    device_transform=scaler.as_device_transform("bfloat16"),
)
net.fit(it, num_epochs=3)
score = float(net._score)
print("final score:", score)

# same data through the reference-style host-side f32 path — identical
# model (fixed seed => identical init)
xf = x8.astype(np.float32) / 255.0
net2 = build_net()
itf = ArraysDataSetIterator((xf, y), batch_size=64)
net2.fit(AsyncDataSetIterator(itf, queue_size=4), num_epochs=3)
print("host-f32 score:", float(net2._score))

print(np.isfinite(score) and score > 0)
