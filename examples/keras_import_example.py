"""Keras import: load the committed real-Keras HDF5 fixture (a functional
residual model) into a ComputationGraph and run inference.

(reference pattern: deeplearning4j-modelimport KerasModelImport)
"""
import _common  # noqa: F401

import os

import numpy as np

from deeplearning4j_tpu.keras.keras_import import \
    import_keras_model_and_weights

fixtures = os.path.join(os.path.dirname(__file__), "..", "tests",
                        "fixtures")
net = import_keras_model_and_weights(
    os.path.join(fixtures, "keras_toy_residual.h5"))
io = np.load(os.path.join(fixtures, "keras_toy_residual_io.npz"))
out = np.asarray(net.output(io["x"])[0])
print("imported model output shape:", out.shape)
print("matches Keras prediction:",
      bool(np.allclose(out, io["y"], atol=1e-4)))
