"""Shared example setup.

Examples default to the CPU backend with a virtual 8-device mesh so every
script runs anywhere (several demonstrate multi-device parallelism). Set
DL4J_EXAMPLES_HW=1 to use whatever accelerator the environment configures
instead (single-accelerator hosts can't run the mesh examples).
"""
import os

if not os.environ.get("DL4J_EXAMPLES_HW"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()

import sys  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# a sitecustomize may have pinned a hardware platform before env vars are
# read; the config update wins (same pattern as tests/conftest.py)
import jax  # noqa: E402

jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
