"""Interleaved ResNet-50 (batch x remat) sweep — the round-5 MFU push.

The r5 on-chip A/B showed remat LOSES 16% at batch 128 (2,209 vs 2,633
img/s): with HBM headroom to spare, segment recompute is pure added FLOPs.
But remat's actual purpose is shrinking the activation working set so a
LARGER batch fits behind the bandwidth wall — the r3 sweep showed plain
batch 256 regressing (~2,535) from spill. This measures whether
remat@256/384 beats the plain batch-128 champion, interleaved so tunnel
drift can't bias an arm.

One JSON line per (batch, remat) arm + a final "winner" line.
Usage: python tools/remat_batch_sweep.py [--budget SECONDS]
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(budget_s=900.0):
    t0 = time.perf_counter()
    import jax
    import numpy as np

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.models.zoo.resnet import resnet50

    print(json.dumps({"sweep": "remat_batch",
                      "platform": jax.devices()[0].platform}), flush=True)
    rng = np.random.default_rng(0)

    ARMS = [(128, False), (256, False), (256, True), (384, True)]
    nets, data = {}, {}
    for batch, remat in ARMS:
        net = resnet50(data_type="bfloat16", remat=remat)
        x = rng.random((batch, 224, 224, 3)).astype(np.float32)
        y = np.eye(1000, dtype=np.float32)[rng.integers(0, 1000, batch)]
        ds = DataSet(jax.device_put(x), jax.device_put(y))
        try:
            net.fit(ds)            # compile (cache-shared across arms)
            float(net._score)
        except Exception as e:     # noqa: BLE001 — e.g. OOM at 384
            print(json.dumps({"batch": batch, "remat": remat,
                              "error": repr(e)[:200]}), flush=True)
            continue
        nets[(batch, remat)] = net
        data[(batch, remat)] = ds

    best = {}
    for seg in range(3):           # interleaved best-of-3 segments
        for key, net in nets.items():
            if time.perf_counter() - t0 > budget_s:
                break
            batch, remat = key
            iters = max(4, 1536 // batch)
            ds = data[key]
            net.fit(ds)            # warm after the previous arm's eviction
            float(net._score)
            t = time.perf_counter()
            for _ in range(iters):
                net.fit(ds)
            float(net._score)
            ips = batch * iters / (time.perf_counter() - t)
            best[key] = max(best.get(key, 0.0), ips)
            print(json.dumps({"batch": batch, "remat": remat, "seg": seg,
                              "images_per_sec": round(ips, 1)}), flush=True)
    if best:
        (batch, remat), ips = max(best.items(), key=lambda kv: kv[1])
        print(json.dumps({"winner": {"batch": batch, "remat": remat,
                                     "images_per_sec": round(ips, 1)}}),
              flush=True)


if __name__ == "__main__":
    budget = 900.0
    if "--budget" in sys.argv:
        budget = float(sys.argv[sys.argv.index("--budget") + 1])
    main(budget)
