"""Repo tooling. A package so `python -m tools.analyze` resolves;
the sibling scripts (load_sweep.py, serve_ab.py, ...) stay directly
runnable."""
