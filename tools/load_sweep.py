"""Throughput–latency sweep: offered rate -> what the servers deliver.

The traffic-harness headline tool: drive a REAL server
(`ContinuousDecodeServer` and/or `InferenceServer`) with seeded arrival
schedules (`serving/loadgen.py`) at a ladder of offered rates, and emit
the curve every serving claim should be judged on:

  offered rate -> achieved tokens/s (requests/s for the micro-batch
  server), request p50/p99, TTFT p99, inter-token p99, SLO attainment,
  goodput-under-SLO, shed counts, submit-lateness (open-loop fidelity)

plus the SATURATION KNEE — the highest offered rate the server still
sustains (achieved >= 90% of offered). Below the knee latency is flat;
past it the queue grows without bound and p99/sheds are the story. The
combined `tools/obs_report.py` view (host spans + span-derived latency
decomposition + per-rate metrics) is written with `--report`.

Run (CPU backend, no chip needed):

    JAX_PLATFORMS=cpu python tools/load_sweep.py \
        [--server both] [--rates 50,100,200,400,800] \
        [--process poisson|onoff|closed] [--requests 64] \
        [--slo-ms 150] [--seed 0] [--report /tmp/sweep] [--no-trace] \
        [--chunked-prefill C] [--admission] [--overload-ab] \
        [--paged] [--speculate K] [--preempt] [--fleet N]
        [--fleet-control [--fleet-min A --fleet-max B]]
        [--fleet-procs N [--chaos [--chaos-events E] [--cascade]]]
        [--affinity [--fleet-procs N]]

`--process onoff` keeps the same MEAN rate but bursts at 2x with a 50%
duty cycle (the p99 stressor); `--process closed` reinterprets each
"rate" as a fixed concurrency (the coordinated-omission contrast).
`--overload-ab` replays the decode ladder through an uncontrolled
baseline AND a chunked-prefill + deadline-admission arm (PR 9) and
appends a comparison record: per-rate goodput/TTFT both arms, the
controlled arm's shed-reason breakdown, and the monotonicity verdict
(goodput must not collapse past the knee).
`--cascade` (with `--chaos`, `--fleet-procs` >= 3) runs the
blast-radius-containment arm: poison-pill quarantine, the spawn
circuit breaker's factory-failure window, and the shared retry budget
composed with the manager kill (ISSUE 17).
`bench.py`'s `load_sweep` config pins one sweep point per record;
tests/test_loadgen.py runs the smoke version in tier-1 and CI uploads
its report JSON.
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from deeplearning4j_tpu.obs.registry import fmt  # noqa: E402

KNEE_THRESH = 0.9


def _lm():
    import jax.numpy as jnp

    from deeplearning4j_tpu.models.zoo.transformer import TransformerLM
    return TransformerLM(96, d_model=32, n_heads=2, n_layers=2,
                         max_len=64, seed=5, dtype=jnp.float32)


def _mlp():
    from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    conf = (NeuralNetConfiguration.Builder().seed(7)
            .updater("adam").learning_rate(0.01).list()
            .layer(0, DenseLayer(n_out=64, activation="relu"))
            .layer(1, OutputLayer(n_out=10, activation="softmax",
                                  loss_function="mcxent"))
            .set_input_type(InputType.feed_forward(32))
            .build())
    return MultiLayerNetwork(conf).init()


def _process_for(process, rate):
    """Map one sweep 'rate' onto an arrival process. onoff keeps the
    same MEAN rate but bursts at 2x with a 50% duty cycle; closed
    reinterprets rate as a concurrency level."""
    from deeplearning4j_tpu.serving import (ClosedLoop, OnOffProcess,
                                            PoissonProcess)
    if process == "poisson":
        return PoissonProcess(rate)
    if process == "onoff":
        return OnOffProcess(2.0 * rate, on_s=0.5, off_s=0.5)
    if process == "closed":
        return ClosedLoop(max(1, int(rate)))
    raise ValueError(f"unknown process {process!r}")


def _knee(curve):
    """Saturation knee over annotated points (each carries `_offered` /
    `_achieved`): the last point before the first unsustained one."""
    knee = first_bad = None
    for pt in curve:
        off, ach = pt.pop("_offered", None), pt.pop("_achieved", None)
        if not off or ach is None:
            continue
        pt["sustained_ratio"] = round(ach / off, 3)
        if first_bad is None:
            if ach / off >= KNEE_THRESH:
                knee = pt
            else:
                first_bad = pt
    return {
        "criterion": f"achieved >= {KNEE_THRESH:g} x offered",
        "knee_offered_rate": knee and knee["offered_rate_target"],
        "knee_achieved": knee and (knee.get("tokens_per_sec")
                                   or knee.get("requests_per_sec")),
        "first_unsustained_rate": (
            first_bad and first_bad["offered_rate_target"]),
    }


def sweep_decode(rates, n_req=64, slo_ms=150.0, seed=0,
                 process="poisson", tracer=None, lm=None, slots=4,
                 paged=False, block_size=8, chunked_prefill=None,
                 admission=None, brownout=None, deadline_ms=None,
                 speculate_k=None, preempt=False, fused_serve=None):
    """Rate ladder over the ContinuousDecodeServer. One server serves
    every rate (compile once); per-point accounting is delta-based
    (loadgen baselines at entry), so points never contaminate each
    other. Offered/achieved compare in TOKENS/s — the decode server's
    capacity is token throughput, not request admission.

    `paged=True` swaps in the block-table KV cache (serving/kvpool.py)
    at the default equal-bytes arena: the same sweep drives the
    block-gated admission path instead of the slot-gated one — the
    tier-1 smoke sweep runs one paged rate so CI exercises it.

    `speculate_k=K` adds a K-wide n-gram speculative decode (both
    layouts — paged speculation is the ISSUE 10 composition; the
    tier-1 smoke sweep runs one paged+speculate rate so CI exercises
    the block-table verify program under real arrivals).

    `fused_serve=K` scans K decode iterations into one device dispatch
    (ISSUE 18 — both layouts; excludes speculate_k, the server refuses
    the combination loudly). The tier-1 smoke sweep runs one
    fused_serve=4 rate so CI exercises the windowed scheduler under
    real arrivals, deadlines included.

    `n_req` may be a sequence (one count per rate): the overload A/B
    scales requests WITH rate so every rung offers the same DURATION of
    traffic — at a fixed count, higher rates compress the arrival
    window and the total in-SLO-completable work shrinks with rate, so
    absolute goodput would decline past the knee for ANY controller
    (a finite-burst accounting artifact, not an overload verdict).

    Overload-control arm (PR 9): `chunked_prefill=C` slices prompts
    into C-row chunks, `admission=True` (or an AdmissionController)
    sheds predicted deadline misses at enqueue, and `deadline_ms` gives
    every request a real deadline (default: the SLO itself, the
    goodput-under-SLO semantics made enforceable) — together the
    protected arm of the `--overload-ab` comparison."""
    from deeplearning4j_tpu.serving import (BrownoutPolicy,
                                            ContinuousDecodeServer,
                                            DecodeSizeMix, NGramDraft,
                                            ServingMetrics, Speculator,
                                            build_schedule, run_load)
    lm = lm if lm is not None else _lm()
    metrics = ServingMetrics(slo_target_ms=slo_ms)
    if preempt:
        # preemption needs the paged pool (a block set to spill) and a
        # class ranking; the sweep's canonical mixed-class shape is the
        # short/long split below with the long tail as batch class
        paged = True
        if brownout is None:
            brownout = BrownoutPolicy(classes={"batch": (0.9, 1.01)})
    controlled = (chunked_prefill is not None or admission or
                  brownout is not None)
    spec = (None if speculate_k is None
            else Speculator(NGramDraft(n=3), k=int(speculate_k)))
    srv = ContinuousDecodeServer(
        lm, slots=slots, prompt_buckets=(8, 16), max_queue=1024,
        metrics=metrics, tracer=tracer, paged=paged,
        block_size=block_size, chunked_prefill=chunked_prefill,
        admission=admission, brownout=brownout, speculate=spec,
        preempt=preempt, fused_serve=fused_serve,
        default_deadline_ms=(deadline_ms if deadline_ms is not None
                             else (slo_ms if admission else None))
        ).start()
    # mostly short chat turns + a tail of long generations — the mixed-
    # length shape continuous batching exists for. With preemption the
    # same split becomes the mixed-CLASS shape: the short turns are the
    # interactive class whose TTFT preemption bounds, the long tail is
    # the preemptible batch class.
    if preempt:
        mix = DecodeSizeMix(((0.8, (3, 12), (4, 24), "interactive"),
                             (0.2, (8, 16), (24, 44), "batch")),
                            vocab=96)
    else:
        mix = DecodeSizeMix(((0.8, (3, 12), (4, 24)),
                             (0.2, (8, 16), (24, 44))), vocab=96)
    try:
        # compile both prompt buckets + the decode step off the clock
        # (explicit generous deadline: the controlled arm's DEFAULT
        # deadline is the SLO, which first-compile latency would blow)
        for p in ([1, 2, 3, 4], list(range(1, 13))):
            srv.generate(p, 4, deadline_ms=600_000, timeout=300)
        curve = []
        n_reqs = (list(n_req) if isinstance(n_req, (list, tuple))
                  else [n_req] * len(rates))
        for i, rate in enumerate(rates):
            sched = build_schedule(_process_for(process, rate), mix,
                                   n_reqs[i], seed=seed + i)
            pt = run_load(srv, sched)
            pt["offered_rate_target"] = rate
            pt["_offered"] = pt["schedule"]["offered_tokens_per_sec"]
            pt["_achieved"] = pt["tokens_per_sec"]
            curve.append(pt)
        snap = metrics.snapshot()
    finally:
        srv.stop(timeout=120)
    # describe the model actually measured (bench.py passes bigger ones)
    d_model = int(lm.aux["tok"].shape[1])
    cache = (f"paged bs={block_size}" if paged else "fixed-slot")
    ctrl = ""
    if controlled:
        ctrl = (f", overload control: chunk={chunked_prefill} "
                f"admission={'on' if admission else 'off'} "
                f"deadline={deadline_ms if deadline_ms is not None else slo_ms:g}ms")
    if spec is not None:
        ctrl += f", speculate k={spec.k} (n-gram)"
    if preempt:
        ctrl += ", preempt=on (batch class spillable)"
    if fused_serve is not None and int(fused_serve) > 1:
        ctrl += f", fused_serve={int(fused_serve)}"
    return {"server": "decode", "process": process, "paged": bool(paged),
            "overload_control": bool(controlled),
            "speculate_k": speculate_k, "preempt": bool(preempt),
            "fused_serve": fused_serve,
            "config": f"TransformerLM L={len(lm.blocks)} d={d_model} "
                      f"slots={slots} cache={cache}, mix 80% "
                      f"short(p3-11/n4-23) + 20% long(p8-15/n24-43), "
                      f"{n_req} reqs/rate, slo={slo_ms:g}ms{ctrl}",
            "unit": "generated tokens/sec",
            "curve": curve, "knee": _knee(curve)}, snap


def sweep_fleet(rates, n_replicas=2, n_req=64, slo_ms=250.0, seed=0,
                process="poisson", trace=True, slots=2, lm=None,
                obs_per_rate=6, slice_s=0.25, signal=None):
    """Rate ladder over N in-process `ContinuousDecodeServer` replicas
    behind a round-robin splitter — the `--fleet N` scenario that
    exercises the whole fleet observability plane end to end:

      * every replica is a NAMED instance (`instance="i<k>"`): its
        metrics federate under that name, its tracer exports its own
        process group, and its request ids are fleet-unique;
      * each rate rung is served as `obs_per_rate` schedule slices;
        after each slice the merged fleet snapshot
        (`obs.fleet.FleetView` over every replica's kind_snapshot) is
        fed to ONE `AutoscaleSignal`, so the ladder drives the
        detector through a real two-regime trace: below the knee sheds
        stay quiet (hold), past it `shed_predicted` accrues while the
        fleet service-rate estimate stays flat at capacity (scale_up —
        the tier-1 fleet smoke pins exactly this);
      * replicas run deadline-aware admission (deadline = SLO), the
        shed_predicted producer the detector reads.

    Returns (body, per_instance_snaps, merged_trace_or_None): `body`
    carries the per-rate curve (each point with its in-rung decision
    sequence and final decision) plus the final fleet snapshot;
    `merged_trace` is the clock-anchor-stitched Chrome trace of every
    replica (None with trace=False)."""
    from deeplearning4j_tpu.obs import Tracer
    from deeplearning4j_tpu.obs.fleet import (AutoscaleSignal, FleetView,
                                              merge_traces)
    from deeplearning4j_tpu.serving import (ContinuousDecodeServer,
                                            DecodeSizeMix,
                                            RoundRobinSplitter,
                                            ServingMetrics,
                                            build_schedule, run_load)
    lm = lm if lm is not None else _lm()
    names = [f"i{k}" for k in range(int(n_replicas))]
    tracers = {n: (Tracer(capacity=1 << 15, enabled=True, instance=n)
                   if trace else Tracer(enabled=False, instance=n))
               for n in names}
    sig = signal if signal is not None else AutoscaleSignal()
    servers = []
    mix = DecodeSizeMix(((0.8, (3, 12), (4, 24)),
                         (0.2, (8, 16), (24, 44))), vocab=96)

    def _fleet_snapshot():
        fv = FleetView(signal=sig)
        for n, s in zip(names, servers):
            fv.add(n, s.metrics)
        return fv.snapshot()

    try:
        # construction INSIDE the try: if replica k's constructor or
        # first compile raises, the finally still stops replicas
        # 0..k-1 instead of leaking their serve loops into the caller
        # process (the tier-1 smoke runs in-process)
        for n in names:
            servers.append(ContinuousDecodeServer(
                lm, slots=slots, prompt_buckets=(8, 16), max_queue=1024,
                metrics=ServingMetrics(slo_target_ms=slo_ms, name=n),
                tracer=tracers[n], instance=n, admission=True,
                default_deadline_ms=slo_ms).start())
        # the PR 12 splitter, now the package's own baseline router
        # (serving/fleet.py promoted it; the closed-loop arm below uses
        # the full FleetManager instead)
        splitter = RoundRobinSplitter(servers)
        # compile both prompt buckets off the clock on EVERY replica
        # (each jits its own programs), with a generous deadline so the
        # admission default (the SLO) never sheds a first-compile
        for srv in servers:
            for p in ([1, 2, 3, 4], list(range(1, 13))):
                srv.generate(p, 4, deadline_ms=600_000, timeout=300)
        curve = []
        for i, rate in enumerate(rates):
            # EQUAL OFFERED DURATION per slice (the overload-AB rule):
            # each observation window sustains the offered rate for
            # ~slice_s seconds, so a past-knee rung really backlogs the
            # fleet inside every window instead of lobbing a burst the
            # replicas drain between slices — at a fixed count the
            # detector would never see sheds ACCRUE (measured). n_req
            # keeps a floor for the low-rate rungs; 400/slice caps the
            # submit storm.
            slice_n = max(2, int(n_req) // int(obs_per_rate),
                          min(int(rate * slice_s), 400))
            decisions, toks, dur = [], 0, 0.0
            offered = None
            for k in range(int(obs_per_rate)):
                sched = build_schedule(
                    _process_for(process, rate), mix, slice_n,
                    seed=seed + i * 1000 + k)
                if offered is None:
                    offered = sched.offered_tokens_per_sec()
                pt = run_load(splitter, sched, metrics=None)
                toks += pt["tokens_out"]
                dur += float(pt["duration_s"])
                decisions.append(sig.observe(_fleet_snapshot()))
            snap = _fleet_snapshot()
            point = {
                "offered_rate_target": rate,
                "tokens_per_sec": fmt(toks / dur if dur else 0.0, 1),
                "tokens_out": toks,
                "autoscale_decisions": decisions,
                "autoscale_decision": decisions[-1],
                "fleet_shed_predicted": snap["fleet_shed_predicted"],
                "fleet_service_rate_tokens_per_sec": fmt(
                    snap["fleet_service_rate_tokens_per_sec"], 1),
                "fleet_slo_attainment": fmt(
                    snap["fleet_slo_attainment"], 4),
                "_offered": offered,
                "_achieved": toks / dur if dur else 0.0,
            }
            curve.append(point)
        fleet_snap = _fleet_snapshot()
        snaps = {n: s.metrics.snapshot()
                 for n, s in zip(names, servers)}
    finally:
        for srv in servers:
            srv.stop(timeout=120)
    merged = (merge_traces([tracers[n].chrome_trace() for n in names],
                           names=names) if trace else None)
    d_model = int(lm.aux["tok"].shape[1])
    body = {"server": "fleet", "n_replicas": int(n_replicas),
            "process": process,
            "config": f"{n_replicas}x TransformerLM L={len(lm.blocks)} "
                      f"d={d_model} slots={slots} round-robin, "
                      f"admission deadline={slo_ms:g}ms, "
                      f"{obs_per_rate} observation slices/rate",
            "unit": "generated tokens/sec (fleet)",
            "curve": curve, "knee": _knee(curve),
            "fleet": fleet_snap,
            "autoscale_transitions": sig.transitions}
    return body, snaps, merged


def sweep_fleet_control(rates, n_replicas=2, n_req=64, slo_ms=250.0,
                        seed=0, process="poisson", trace=True, slots=2,
                        lm=None, obs_per_rate=6, slice_s=0.25,
                        signal=None, fault_injector=None,
                        min_replicas=None, max_replicas=None):
    """The CLOSED-LOOP fleet arm (`--fleet-control`): the same rate
    ladder as `sweep_fleet`, but replica count is driven by a
    `serving.fleet.FleetManager` — each schedule slice ends in one
    `control_tick()` that federates the fleet snapshot, consults the
    `AutoscaleSignal`, and ACTS (scale_up spawns a warmed replica,
    scale_down drains one with live-request migration; replica deaths
    — injected via `fault_injector` at the `fleet.replica` site — fail
    over in-flight requests to survivors by prompt replay).

    The convergence record (`body["fleet_control"]`) carries the
    ISSUE 13 pins: within the first rung that scaled up, mean
    per-slice goodput AFTER the spawn vs BEFORE it
    (`goodput_recovery_x` — the added replica must recover >= 0.8x,
    and in practice exceeds 1x, of the saturated pre-scale goodput),
    and the quiet-tail return to `min_replicas`
    (`returned_to_min`). Default signal: AutoscaleSignal(window=4,
    hysteresis=1) — the reset-after-action rule makes a short window
    safe (one action per argued regime), and the smoke budget needs
    decisions inside a 6-slice rung."""
    from deeplearning4j_tpu.obs import Tracer
    from deeplearning4j_tpu.obs.fleet import AutoscaleSignal, merge_traces
    from deeplearning4j_tpu.serving import (ContinuousDecodeServer,
                                            DecodeSizeMix, FleetManager,
                                            ServingMetrics,
                                            build_schedule, run_load)
    lm = lm if lm is not None else _lm()
    tracers = {}

    def factory(name):
        tr = tracers[name] = (
            Tracer(capacity=1 << 15, enabled=True, instance=name)
            if trace else Tracer(enabled=False, instance=name))
        return ContinuousDecodeServer(
            lm, slots=slots, prompt_buckets=(8, 16), max_queue=1024,
            metrics=ServingMetrics(slo_target_ms=slo_ms, name=name),
            tracer=tr, instance=name, admission=True,
            default_deadline_ms=slo_ms)

    def warmup(srv):
        # compile both prompt buckets + the decode step off the
        # serving clock on EVERY spawn (a cold spawned replica would
        # blow its first requests' SLO on compiles, reading as a
        # degraded replica the moment it joins)
        for p in ([1, 2, 3, 4], list(range(1, 13))):
            srv.generate(p, 4, deadline_ms=600_000, timeout=300)

    sig = signal if signal is not None else AutoscaleSignal(
        window=4, hysteresis=1)
    mgr = FleetManager(factory, n_replicas=n_replicas, signal=sig,
                       fault_injector=fault_injector, warmup=warmup,
                       min_replicas=min_replicas,
                       max_replicas=max_replicas,
                       metrics=ServingMetrics(name="fleet"))
    mix = DecodeSizeMix(((0.8, (3, 12), (4, 24)),
                         (0.2, (8, 16), (24, 44))), vocab=96)
    curve = []
    scale_rung = None       # (rung index, slice goodputs pre/post)
    try:
        mgr.start()
        for i, rate in enumerate(rates):
            # EQUAL OFFERED DURATION per slice (the sweep_fleet rule)
            slice_n = max(2, int(n_req) // int(obs_per_rate),
                          min(int(rate * slice_s), 400))
            ticks, goodputs = [], []
            toks, dur, offered = 0, 0.0, None
            admitted = completed = failed = 0
            for k in range(int(obs_per_rate)):
                sched = build_schedule(
                    _process_for(process, rate), mix, slice_n,
                    seed=seed + i * 1000 + k)
                if offered is None:
                    offered = sched.offered_tokens_per_sec()
                g0 = mgr.fleet_view().counter("slo_tokens_met")
                pt = run_load(mgr, sched, metrics=None)
                toks += pt["tokens_out"]
                dur += float(pt["duration_s"])
                admitted += pt["admitted"]
                completed += pt["completed"]
                failed += pt["failed"]
                g1 = mgr.fleet_view().counter("slo_tokens_met")
                goodputs.append(
                    (g1 - g0) / max(float(pt["duration_s"]), 1e-9))
                ticks.append(mgr.control_tick())
            if scale_rung is None and any(
                    t["acted"] == "scale_up" for t in ticks):
                at = next(k for k, t in enumerate(ticks)
                          if t["acted"] == "scale_up")
                scale_rung = {"rung": i, "slice": at,
                              "pre": goodputs[:at + 1],
                              "post": goodputs[at + 1:]}
            snap = mgr.fleet_snapshot()
            curve.append({
                "offered_rate_target": rate,
                "tokens_per_sec": fmt(toks / dur if dur else 0.0, 1),
                "tokens_out": toks,
                "admitted": admitted, "completed": completed,
                "failed": failed,
                "slice_goodput_tokens_per_sec": [fmt(g, 1)
                                                 for g in goodputs],
                "autoscale_decisions": [t["decision"] for t in ticks],
                "autoscale_acted": [t["acted"] for t in ticks],
                "n_replicas": [t["n_replicas"] for t in ticks],
                "fleet_shed_predicted": snap["fleet_shed_predicted"],
                "_offered": offered,
                "_achieved": toks / dur if dur else 0.0,
            })
        final_snap = mgr.fleet_snapshot()
        snaps = {n: mgr.replica(n).metrics.snapshot()
                 for n in mgr.replicas}
        states = mgr.states()
        n_final = mgr.n_alive()
    finally:
        mgr.stop(timeout=120)
    merged = (merge_traces([t.chrome_trace() for t in tracers.values()],
                           names=list(tracers))
              if trace and tracers else None)
    recovery = None
    if scale_rung and scale_rung["pre"] and scale_rung["post"]:
        pre = sum(scale_rung["pre"]) / len(scale_rung["pre"])
        post = sum(scale_rung["post"]) / len(scale_rung["post"])
        recovery = (post / pre) if pre > 0 else None
    d_model = int(lm.aux["tok"].shape[1])
    body = {"server": "fleet_control", "n_replicas": int(n_replicas),
            "process": process,
            "config": f"FleetManager over {n_replicas}x TransformerLM "
                      f"L={len(lm.blocks)} d={d_model} slots={slots}, "
                      f"least-backlog router, admission deadline="
                      f"{slo_ms:g}ms, {obs_per_rate} control ticks/"
                      f"rate, min={mgr.min_replicas} "
                      f"max={mgr.max_replicas}",
            "unit": "generated tokens/sec (fleet)",
            "curve": curve, "knee": _knee(curve),
            "fleet": final_snap,
            "fleet_control": {
                "replica_spawned": final_snap["fleet_replica_spawned"],
                "replica_drained": final_snap["fleet_replica_drained"],
                "replica_dead": final_snap["fleet_replica_dead"],
                "failover_resubmitted":
                    final_snap["fleet_failover_resubmitted"],
                "scale_up_at": ({"rung": scale_rung["rung"],
                                 "slice": scale_rung["slice"]}
                                if scale_rung else None),
                "goodput_recovery_x": fmt(recovery, 3),
                # the ISSUE 13 convergence criterion; captures land
                # well above it (an added replica raises capacity ~1.5x)
                "goodput_recovered_08": (recovery is not None
                                         and recovery >= 0.8),
                "n_replicas_final": n_final,
                "returned_to_min": n_final == mgr.min_replicas,
                "states": states},
            "autoscale_transitions": sig.transitions}
    return body, snaps, merged


def _replica_serve_main(argv):
    """Child-process entry for `--fleet-procs` (hidden flag
    `--replica-serve`): build the SAME deterministic model the parent
    knows (fixed seed ⇒ identical weights ⇒ identical param
    fingerprint across processes — migrations tag-check against it),
    wrap one decode server in a `ReplicaServer`, publish the bound
    port, and serve until the parent's STOP/KILL/DRAIN. A graceful
    exit saves this process's own Chrome trace — the parent stitches
    every replica's file into ONE merged timeline."""
    ap = argparse.ArgumentParser()
    ap.add_argument("--replica-serve", action="store_true")
    ap.add_argument("--instance", required=True)
    ap.add_argument("--port-file", required=True)
    ap.add_argument("--trace-out", default=None)
    ap.add_argument("--identity-file", default=None)
    ap.add_argument("--slo-ms", type=float, default=250.0)
    ap.add_argument("--slots", type=int, default=2)
    ap.add_argument("--paged", action="store_true")
    ap.add_argument("--prompt-buckets", default="8,16",
                    help="comma-separated prefill bucket rows (the "
                         "affinity arm's shared-prefix prompts need "
                         "16,32)")
    args = ap.parse_args(argv)
    from deeplearning4j_tpu.obs import Tracer
    from deeplearning4j_tpu.serving import (ContinuousDecodeServer,
                                            ServingMetrics,
                                            run_replica_server)
    lm = _lm()
    tr = Tracer(capacity=1 << 15, enabled=args.trace_out is not None,
                instance=args.instance)
    buckets = tuple(int(b) for b in args.prompt_buckets.split(","))
    srv = ContinuousDecodeServer(
        lm, slots=args.slots, prompt_buckets=buckets, max_queue=1024,
        metrics=ServingMetrics(slo_target_ms=args.slo_ms,
                               name=args.instance),
        tracer=tr, instance=args.instance, admission=True,
        default_deadline_ms=args.slo_ms, paged=args.paged, block_size=8)
    run_replica_server(srv, port_file=args.port_file, tracer=tr,
                       trace_out=args.trace_out,
                       identity_file=args.identity_file)


def sweep_fleet_procs(rates, n_replicas=2, n_req=64, slo_ms=250.0,
                      seed=0, process="poisson", trace=True, slots=2,
                      obs_per_rate=4, slice_s=0.2, fault_injector=None,
                      inject_sever=True, paged=False,
                      sever_site="serve.wire.stream"):
    """The CROSS-PROCESS fleet arm (`--fleet-procs N`): every replica
    is a REAL child process (`--replica-serve`) behind a
    `serving.wire.RemoteReplica`, routed by the same `FleetManager`
    the in-process sweeps use — the whole wire path (SUBMIT/STREAM
    frames, SNAPSHOT-federated metrics, heartbeat liveness,
    reconnect-with-dedup) under real arrivals.

    After the rate rungs, the FAULT PHASE injects one socket sever at
    `sever_site` (default: the result frame mid-stream) while a batch
    of requests is in flight and pins the ISSUE 14 acceptance: every
    admitted future resolves, and the faulted prompt's stream is
    BIT-IDENTICAL to the same prompt served on the quiet fleet
    (deterministic greedy ⇒ dedup re-delivery and failover replay are
    indistinguishable from an undisturbed run). The record carries the
    wire counters (`wire_reconnects`/`wire_retries`) so the sever is
    visibly exercised, and the merged trace covers every replica
    PROCESS (distinct pids in Perfetto).

    Returns (body, per_instance_snaps, merged_trace_or_None)."""
    import subprocess
    import tempfile

    from deeplearning4j_tpu.common.resilience import (FaultInjector,
                                                      RetryPolicy)
    from deeplearning4j_tpu.obs.fleet import merge_traces
    from deeplearning4j_tpu.serving import (DecodeSizeMix, FleetManager,
                                            RemoteReplica,
                                            ServingMetrics,
                                            build_schedule, run_load)
    if fault_injector is None and inject_sever:
        fault_injector = FaultInjector()
    tmpdir = tempfile.mkdtemp(prefix="fleet_procs_")
    here = os.path.abspath(__file__)
    procs, trace_files = {}, {}

    def launch(name):
        port_file = os.path.join(tmpdir, f"{name}.port")
        trace_out = (os.path.join(tmpdir, f"{name}.trace.json")
                     if trace else None)
        cmd = [sys.executable, here, "--replica-serve",
               "--instance", name, "--port-file", port_file,
               "--slo-ms", str(slo_ms), "--slots", str(slots)]
        if paged:
            # paged children make drains MIGRATE artifact bytes over
            # the wire (non-paged replicas degrade drains to replay)
            cmd.append("--paged")
        if trace_out:
            cmd += ["--trace-out", trace_out]
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        procs[name] = subprocess.Popen(cmd, env=env)
        trace_files[name] = trace_out
        return port_file

    def wait_port(name, port_file, timeout=300.0):
        t0 = time.monotonic()
        while time.monotonic() - t0 < timeout:
            if os.path.exists(port_file):
                return int(open(port_file).read().strip())
            if procs[name].poll() is not None:
                raise RuntimeError(
                    f"replica process {name} exited rc="
                    f"{procs[name].returncode} before binding")
            time.sleep(0.05)
        raise TimeoutError(f"replica {name} never published its port")

    names = [f"i{k}" for k in range(int(n_replicas))]
    # pre-launch every expected replica so the N jax imports + compiles
    # overlap instead of serializing through the factory
    ports = {n: launch(n) for n in names}

    def factory(name):
        port_file = ports.pop(name, None)
        if port_file is None:
            port_file = launch(name)        # backfill beyond the batch
        port = wait_port(name, port_file)
        return RemoteReplica(
            "127.0.0.1", port, name=name,
            retry_policy=RetryPolicy(max_retries=4, base_delay=0.05,
                                     max_delay=0.5, jitter=0.0),
            heartbeat_interval=0.1, fault_injector=fault_injector,
            process=procs[name])

    def warmup(srv):
        # compile the child's prompt buckets + decode step off the
        # serving clock, over the wire
        for p in ([1, 2, 3, 4], list(range(1, 13))):
            srv.generate(p, 4, deadline_ms=600_000, timeout=300)

    mgr = FleetManager(factory, n_replicas=n_replicas, warmup=warmup,
                       heartbeat_timeout=2.0,
                       metrics=ServingMetrics(name="fleet"))
    mix = DecodeSizeMix(((0.8, (3, 12), (4, 24)),
                         (0.2, (8, 16), (24, 44))), vocab=96)
    curve = []
    try:
        mgr.start()
        for i, rate in enumerate(rates):
            slice_n = max(2, int(n_req) // int(obs_per_rate),
                          min(int(rate * slice_s), 400))
            toks, dur, offered = 0, 0.0, None
            admitted = completed = failed = 0
            for k in range(int(obs_per_rate)):
                sched = build_schedule(
                    _process_for(process, rate), mix, slice_n,
                    seed=seed + i * 1000 + k)
                if offered is None:
                    offered = sched.offered_tokens_per_sec()
                pt = run_load(mgr, sched, metrics=None)
                toks += pt["tokens_out"]
                dur += float(pt["duration_s"])
                admitted += pt["admitted"]
                completed += pt["completed"]
                failed += pt["failed"]
                mgr.control_tick()          # the health/liveness probe
            curve.append({
                "offered_rate_target": rate,
                "tokens_per_sec": fmt(toks / dur if dur else 0.0, 1),
                "tokens_out": toks,
                "admitted": admitted, "completed": completed,
                "failed": failed,
                "_offered": offered,
                "_achieved": toks / dur if dur else 0.0,
            })
        # -- FAULT PHASE: one injected socket sever mid-stream --------
        fault_rec = None
        if inject_sever and fault_injector is not None:
            # quiet-fleet references first: deterministic greedy on
            # identical weights makes every replica's stream for a
            # prompt THE stream, so the fault batch must reproduce
            # them bit-for-bit no matter which request the sever hits
            prompts = [[1, 2, 3]] + [[4 + j, 5, 6] for j in range(5)]
            refs = [list(mgr.generate(p, 24, deadline_ms=600_000,
                                      timeout=300)) for p in prompts]
            base = mgr.fleet_snapshot()
            fault_injector.plan(sever_site,
                                on_call=fault_injector.calls(sever_site),
                                sever=True, exc=None)
            futs = [mgr.submit(p, 24, deadline_ms=600_000)
                    for p in prompts]
            results = [list(f.result(300)) for f in futs]  # ALL resolve
            snap = mgr.fleet_snapshot()
            fault_rec = {
                "site": sever_site,
                "severed": len(fault_injector.fired(sever_site)),
                "all_futures_resolved": True,
                "streams_bit_identical": results == refs,
                "wire_reconnects": snap["fleet_wire_reconnects"]
                - base["fleet_wire_reconnects"],
                "wire_retries": snap["fleet_wire_retries"]
                - base["fleet_wire_retries"],
            }
        final_snap = mgr.fleet_snapshot()
        snaps = {n: mgr.replica(n).metrics.snapshot()
                 for n in mgr.replicas}
        pids = {n: procs[n].pid for n in procs}
    finally:
        mgr.stop(timeout=120)
        for p in procs.values():        # belt and braces
            if p.poll() is None:
                p.terminate()
        for p in procs.values():
            try:
                p.wait(timeout=30)
            except Exception:   # noqa: BLE001
                p.kill()
    merged = None
    if trace:
        saved = []
        tnames = []
        for n, path in trace_files.items():
            if path and os.path.exists(path):
                with open(path) as fh:
                    saved.append(json.load(fh))
                tnames.append(n)
        if saved:
            merged = merge_traces(saved, names=tnames)
    # the scratch dir (port files + per-replica traces) is spent once
    # the traces are merged — repeated sweeps must not accumulate it
    shutil.rmtree(tmpdir, ignore_errors=True)
    body = {"server": "fleet_procs", "n_replicas": int(n_replicas),
            "process": process, "paged": bool(paged),
            "config": f"FleetManager over {n_replicas} replica "
                      f"PROCESSES (serving/wire.py), slots={slots}, "
                      f"cache={'paged bs=8' if paged else 'fixed-slot'}"
                      f", admission deadline={slo_ms:g}ms, heartbeat "
                      f"timeout 2s, {obs_per_rate} slices/rate",
            "unit": "generated tokens/sec (fleet)",
            "curve": curve, "knee": _knee(curve),
            "fleet": final_snap,
            "replica_pids": pids,
            "wire_fault": fault_rec}
    return body, snaps, merged


def sweep_fleet_affinity(rates, n_replicas=3, n_req=48, slo_ms=250.0,
                         seed=0, process="poisson", trace=False,
                         slots=2, lm=None, obs_per_rate=2,
                         slice_s=0.25, procs=0, n_prefixes=4,
                         dispatch_reqs=10):
    """The PREFIX-AFFINITY arm (`--affinity`, ISSUE 20): a seeded
    shared-system-prompt workload (`serving.loadgen.SharedPrefixMix` —
    P block-aligned prefixes drawn on their own stream) over paged
    replicas, served three ways on IDENTICAL schedules:

      * **solo reference** — ONE paged replica; its prefix hit rate is
        the ceiling any router can retain;
      * **affinity** — `FleetManager(policy="affinity")`: consistent-
        hash routing of the block-aligned prefix key with load-aware
        spill, plus the fleet prefix tier (a spilled/missing replica
        PULLS a peer's resident blocks over `prefix_export`/
        `prefix_adopt` instead of recomputing);
      * **least_backlog** — the prefix-blind baseline whose fleet hit
        rate decays toward ~1/N as replicas dilute the cache.

    The record carries the per-arm fleet hit rate (counter DELTAS over
    the measured rungs — warmup traffic excluded), the routing
    verdicts (`routed_affinity`/`routed_spill`), the prefix-tier
    traffic (`prefix_pull_hits`/`_refused`/`_bytes`), goodput per arm,
    and `hit_rate_ratio_vs_solo` — the ISSUE 20 acceptance pins it
    >= 0.9 at 3 replicas.

    The DISPATCH A/B pins the no-pull affinity path at ZERO added
    device dispatches per token: the same fixed request list is served
    one-at-a-time through two fleets-of-one — `policy="affinity"`
    (prefix_pull off) vs `policy="least_backlog"` — and the
    `dispatches`+`chunk_dispatches` deltas must match exactly (routing
    is host-side hashing; nothing touches the device).

    `procs=N` (the `--fleet-procs N --affinity` spelling) runs the two
    FLEET arms as N real replica PROCESSES behind the serving wire —
    block pulls become PREFIX_PULL/PREFIX_PUSH artifact frames — while
    the solo reference and dispatch A/B stay in-process (they measure
    cache/compute properties the wire cannot change). Span tracing is
    not wired through this arm (`trace` is accepted for signature
    parity); the counters are the record. Returns
    (body, per_instance_snaps, None)."""
    import random
    import subprocess
    import tempfile

    from deeplearning4j_tpu.common.resilience import RetryPolicy
    from deeplearning4j_tpu.serving import (ContinuousDecodeServer,
                                            FleetManager, RemoteReplica,
                                            ServingMetrics,
                                            SharedPrefixMix,
                                            build_schedule, run_load)
    del trace
    lm = lm if lm is not None else _lm()
    bs = 8
    mix = SharedPrefixMix(n_prefixes=n_prefixes, prefix_blocks=(1, 3),
                          block_size=bs, suffix=(1, 9), new=(4, 16),
                          vocab=96, seed=seed)
    buckets = (16, 32)
    here = os.path.abspath(__file__)
    # the dispatch-A/B request list: drawn ONCE, replayed verbatim
    # through both fleets-of-one (identical work is the whole point)
    rng = random.Random(f"load_sweep.affinity.dispatch:{seed}")
    ab_reqs = [mix.sample(rng) for _ in range(int(dispatch_reqs))]

    def local_factory(name):
        return ContinuousDecodeServer(
            lm, slots=slots, prompt_buckets=buckets, max_queue=1024,
            metrics=ServingMetrics(slo_target_ms=slo_ms, name=name),
            instance=name, admission=True, default_deadline_ms=slo_ms,
            paged=True, block_size=bs)

    def warmup(srv):
        # compile BOTH prefill buckets + the decode step off the
        # serving clock (the shared-prefix prompts span 9..32 rows)
        for p in ([1, 2, 3, 4], list(range(1, 25))):
            srv.generate(p, 4, deadline_ms=600_000, timeout=300)

    TIER_KEYS = ("prefix_rows_hit", "prefix_rows_total",
                 "prefix_pull_hits", "prefix_pull_refused",
                 "prefix_pull_bytes")

    def tier_counters(mgr):
        out = dict.fromkeys(TIER_KEYS, 0)
        for n in list(mgr.replicas):
            snap = mgr.replica(n).metrics.snapshot()
            for k in TIER_KEYS:
                out[k] += int(snap.get(k) or 0)
        return out

    def run_arm(policy, n, use_procs, pull, tag, do_rungs=True,
                do_dispatch=False):
        procs_map, tmpdir = {}, None
        if use_procs:
            tmpdir = tempfile.mkdtemp(prefix=f"fleet_affinity_{tag}_")

            def launch(name):
                port_file = os.path.join(tmpdir, f"{name}.port")
                cmd = [sys.executable, here, "--replica-serve",
                       "--instance", name, "--port-file", port_file,
                       "--slo-ms", str(slo_ms), "--slots", str(slots),
                       "--paged", "--prompt-buckets",
                       ",".join(str(b) for b in buckets)]
                env = dict(os.environ, JAX_PLATFORMS="cpu")
                procs_map[name] = subprocess.Popen(cmd, env=env)
                return port_file

            def wait_port(name, port_file, timeout=300.0):
                t0 = time.monotonic()
                while time.monotonic() - t0 < timeout:
                    if os.path.exists(port_file):
                        return int(open(port_file).read().strip())
                    if procs_map[name].poll() is not None:
                        raise RuntimeError(
                            f"replica process {name} exited rc="
                            f"{procs_map[name].returncode} before "
                            f"binding")
                    time.sleep(0.05)
                raise TimeoutError(
                    f"replica {name} never published its port")

            ports = {f"i{k}": None for k in range(int(n))}
            for name in ports:
                ports[name] = launch(name)

            def factory(name):
                port_file = ports.pop(name, None) or launch(name)
                port = wait_port(name, port_file)
                return RemoteReplica(
                    "127.0.0.1", port, name=name,
                    retry_policy=RetryPolicy(max_retries=4,
                                             base_delay=0.05,
                                             max_delay=0.5, jitter=0.0),
                    heartbeat_interval=0.1, process=procs_map[name])
        else:
            factory = local_factory
        mgr = FleetManager(factory, n_replicas=n, policy=policy,
                           prefix_pull=pull, warmup=warmup,
                           heartbeat_timeout=2.0 if use_procs else None,
                           metrics=ServingMetrics(name="fleet"))
        try:
            mgr.start()
            dispatch_rec = None
            if do_dispatch:
                fv0 = mgr.fleet_view()
                d0 = (fv0.counter("dispatches")
                      + fv0.counter("chunk_dispatches"))
                toks = 0
                for r in ab_reqs:
                    toks += len(mgr.generate(r["prompt"], r["max_new"],
                                             deadline_ms=600_000,
                                             timeout=300))
                fv1 = mgr.fleet_view()
                d1 = (fv1.counter("dispatches")
                      + fv1.counter("chunk_dispatches"))
                dispatch_rec = {"dispatches": d1 - d0, "tokens": toks}
            # steady-state preload: route one request per shared
            # prefix through THIS arm's own policy before the
            # measurement baseline, so every arm measures its steady
            # state rather than its cold start (the dispatch A/B above
            # already warmed the solo arm's single replica — without
            # this the hit-rate comparison would be rigged against the
            # fleet arms, which pay one cold miss per prefix per home)
            for p in mix.prefixes:
                mgr.generate(list(p) + [1, 2], 4, deadline_ms=600_000,
                             timeout=300)
            curve = []
            base = tier_counters(mgr)
            base_fleet = mgr.fleet_snapshot()
            toks_all, dur_all = 0, 0.0
            admitted = completed = failed = 0
            if do_rungs:
                for i, rate in enumerate(rates):
                    slice_n = max(2, int(n_req) // int(obs_per_rate),
                                  min(int(rate * slice_s), 400))
                    toks, dur, offered = 0, 0.0, None
                    adm = com = fai = 0
                    for k in range(int(obs_per_rate)):
                        sched = build_schedule(
                            _process_for(process, rate), mix, slice_n,
                            seed=seed + i * 1000 + k)
                        if offered is None:
                            offered = sched.offered_tokens_per_sec()
                        pt = run_load(mgr, sched, metrics=None)
                        toks += pt["tokens_out"]
                        dur += float(pt["duration_s"])
                        adm += pt["admitted"]
                        com += pt["completed"]
                        fai += pt["failed"]
                    curve.append({
                        "offered_rate_target": rate,
                        "tokens_per_sec": fmt(toks / dur if dur
                                              else 0.0, 1),
                        "tokens_out": toks,
                        "admitted": adm, "completed": com,
                        "failed": fai,
                        "_offered": offered,
                        "_achieved": toks / dur if dur else 0.0,
                    })
                    toks_all += toks
                    dur_all += dur
                    admitted += adm
                    completed += com
                    failed += fai
            tier = tier_counters(mgr)
            fleet_snap = mgr.fleet_snapshot()
            # -- RING-CHURN phase (affinity + pull arms only): spawn
            # replicas until the ring remaps at least one shared
            # prefix onto a newcomer, PREFETCH the moved keys (the
            # fleet tier pulls the warm blocks from their old homes —
            # synchronously, through the same budget and counters the
            # dispatch-time pull uses), then request the moved
            # prefixes: they must HIT on the adopted rows without the
            # newcomer ever recomputing them. Measured AFTER the
            # steady-state counters above so the rung hit rates stay
            # churn-free.
            churn_rec = None
            if policy == "affinity" and pull and do_rungs and n >= 2:
                from deeplearning4j_tpu.serving.fleet import (
                    _build_ring, _ring_hash, _ring_lookup)
                nb = mgr.affinity_block * mgr.affinity_blocks
                keys = [tuple(p[:nb]) for p in mix.prefixes]
                owner0 = {
                    k: _ring_lookup(_build_ring(list(mgr.replicas)),
                                    _ring_hash(k)) for k in keys}
                added, moved = [], []
                for _ in range(4):
                    added.append(mgr.scale_up())
                    ring = _build_ring(list(mgr.replicas))
                    moved = [i for i, k in enumerate(keys)
                             if _ring_lookup(ring, _ring_hash(k))
                             != owner0[k]]
                    if moved:
                        break
                pre = tier_counters(mgr)
                pulled_blocks = sum(
                    mgr.prefetch(list(mix.prefixes[i])) for i in moved)
                h0 = tier_counters(mgr)
                for i in moved:
                    mgr.generate(list(mix.prefixes[i]) + [3, 4], 4,
                                 deadline_ms=600_000, timeout=300)
                post = tier_counters(mgr)
                churn_rec = {
                    "replicas_added": added,
                    "keys_moved": len(moved),
                    "pulled_blocks": pulled_blocks,
                    "prefix_pull_hits": post["prefix_pull_hits"]
                    - pre["prefix_pull_hits"],
                    "prefix_pull_refused": post["prefix_pull_refused"]
                    - pre["prefix_pull_refused"],
                    "prefix_pull_bytes": post["prefix_pull_bytes"]
                    - pre["prefix_pull_bytes"],
                    "rehit_rows_after_pull":
                        post["prefix_rows_hit"] - h0["prefix_rows_hit"],
                }
            snaps = {f"{tag}_{n}": mgr.replica(n).metrics.snapshot()
                     for n in list(mgr.replicas)}
        finally:
            mgr.stop(timeout=120)
            for p in procs_map.values():        # belt and braces
                if p.poll() is None:
                    p.terminate()
            for p in procs_map.values():
                try:
                    p.wait(timeout=30)
                except Exception:   # noqa: BLE001
                    p.kill()
            if tmpdir:
                shutil.rmtree(tmpdir, ignore_errors=True)
        hit = tier["prefix_rows_hit"] - base["prefix_rows_hit"]
        tot = tier["prefix_rows_total"] - base["prefix_rows_total"]
        rec = {
            "policy": policy, "n_replicas": int(n),
            "procs": bool(use_procs), "curve": curve,
            "tokens_per_sec": fmt(toks_all / dur_all if dur_all
                                  else 0.0, 1),
            "admitted": admitted, "completed": completed,
            "failed": failed, "lost": admitted - completed - failed,
            "prefix_rows_hit": hit, "prefix_rows_total": tot,
            "hit_rate": fmt(hit / tot if tot else None, 4),
            "routed_affinity": fleet_snap["fleet_routed_affinity"]
            - base_fleet["fleet_routed_affinity"],
            "routed_spill": fleet_snap["fleet_routed_spill"]
            - base_fleet["fleet_routed_spill"],
            "prefix_pull_hits": tier["prefix_pull_hits"]
            - base["prefix_pull_hits"],
            "prefix_pull_refused": tier["prefix_pull_refused"]
            - base["prefix_pull_refused"],
            "prefix_pull_bytes": tier["prefix_pull_bytes"]
            - base["prefix_pull_bytes"],
            "ring_churn": churn_rec,
            "_achieved": toks_all / dur_all if dur_all else 0.0,
        }
        return rec, snaps, dispatch_rec, fleet_snap

    use_procs = int(procs) >= 2
    n_fleet = int(procs) if use_procs else int(n_replicas)
    # solo reference doubles as the AFFINITY side of the dispatch A/B
    # (a fleet of one routed by the affinity policy IS the solo server,
    # plus the routing code under test)
    solo_rec, solo_snaps, ab_aff, _ = run_arm(
        "affinity", 1, False, False, "solo", do_dispatch=True)
    _, _, ab_base, _ = run_arm(
        "least_backlog", 1, False, False, "dispatch_baseline",
        do_rungs=False, do_dispatch=True)
    aff_rec, aff_snaps, _, aff_fleet = run_arm(
        "affinity", n_fleet, use_procs, True, "affinity")
    lb_rec, lb_snaps, _, _ = run_arm(
        "least_backlog", n_fleet, use_procs, False, "least_backlog")

    def per_tok(rec):
        return rec["dispatches"] / rec["tokens"] if rec["tokens"] \
            else None
    apt, bpt = per_tok(ab_aff), per_tok(ab_base)
    dispatch_ab = {
        "affinity_dispatches": ab_aff["dispatches"],
        "affinity_tokens": ab_aff["tokens"],
        "affinity_dispatches_per_token": fmt(apt, 4),
        "least_backlog_dispatches": ab_base["dispatches"],
        "least_backlog_tokens": ab_base["tokens"],
        "least_backlog_dispatches_per_token": fmt(bpt, 4),
        # the acceptance pin: routing by hash is host-side work — the
        # no-pull affinity path must not add a single device dispatch
        "zero_added_dispatches": (apt is not None and bpt is not None
                                  and apt <= bpt + 1e-9),
    }
    solo_hr = solo_rec["hit_rate"]
    aff_hr = aff_rec["hit_rate"]
    ratio = (aff_hr / solo_hr if solo_hr else None)
    lb_tps = lb_rec["_achieved"]
    goodput_ratio = (aff_rec["_achieved"] / lb_tps if lb_tps else None)
    snaps = {}
    for s in (solo_snaps, aff_snaps, lb_snaps):
        snaps.update(s)
    body = {"server": "fleet_affinity", "n_replicas": n_fleet,
            "process": process, "procs": int(procs),
            "config": f"{n_fleet}x paged bs={bs} "
                      f"{'replica PROCESSES' if use_procs else 'in-process replicas'}"
                      f", SharedPrefixMix P={n_prefixes} "
                      f"blocks=1..2, affinity vs least_backlog vs "
                      f"solo on identical seeded schedules, "
                      f"admission deadline={slo_ms:g}ms",
            "unit": "generated tokens/sec (fleet)",
            "solo": solo_rec, "affinity": aff_rec,
            "least_backlog": lb_rec,
            "hit_rate_ratio_vs_solo": fmt(ratio, 3),
            "hit_rate_retained_09": (ratio is not None
                                     and ratio >= 0.9),
            "goodput_ratio_vs_least_backlog": fmt(goodput_ratio, 3),
            "dispatch_ab": dispatch_ab,
            "curve": aff_rec["curve"], "knee": _knee(aff_rec["curve"]),
            "fleet": aff_fleet}
    return body, snaps, None


def sweep_fleet_chaos(rates, n_replicas=2, n_req=48, slo_ms=250.0,
                      seed=0, process="poisson", trace=False, slots=2,
                      chaos_events=5, slice_s=0.2, cascade=False):
    """The DURABLE-CONTROL-PLANE arm (`--chaos`, needs
    `--fleet-procs N`): the same replica-process fleet as
    `sweep_fleet_procs`, but the manager journals every state
    transition (`serving/fleetjournal.py`) and a SEEDED chaos schedule
    (`serving.loadgen.build_chaos_schedule`) fires between load slices:
    socket severs at the wire fault sites, one injected replica crash,
    and — always — one MANAGER KILL. The kill abandons the live
    `FleetManager` mid-fleet exactly the way a dead process would
    (journal handle gone, sockets half-open) and `FleetManager.recover`
    builds the successor from the journal: live replicas are re-adopted
    over identity-verified HELLOs, the new epoch fences the predecessor
    out (its next control op gets a typed `StaleEpochError`), and any
    shortfall is backfilled.

    The record pins the ISSUE 16 acceptance: every admitted future
    resolves (bit-identical to the quiet-fleet references or failed
    loudly), admitted == completed + failed globally, re-adopted
    replicas' counters stay monotone across the restart, and the
    fenced op is refused with the typed error while zero requests are
    lost. The schedule digest makes the whole run replayable from
    (seed, chaos_events) alone.

    `cascade=True` is the BLAST-RADIUS-CONTAINMENT arm (`--cascade`,
    ISSUE 17): the schedule adds the `poison` action (a request whose
    decode deterministically kills the replica it lands on, via the
    manager's kill hook — two kills convict it, `PoisonPillError`,
    quarantine) and `spawn_fail` (a factory-failure window — the spawn
    circuit breaker opens after K consecutive infant strikes and the
    fleet serves DEGRADED on its survivors instead of crash-looping),
    both composed with the manager kill above. The record pins the
    cascade: the poison request is the ONLY request lost (typed
    verdict), its two kills are the only deaths it causes,
    re-submissions shed at the door (before AND after manager
    recovery — the quarantine is journaled), spawn attempts in the
    breaker window stay <= K, and the accounting still balances.

    Returns (body, per_instance_snaps, merged_trace_or_None)."""
    import concurrent.futures as cf
    import subprocess
    import tempfile

    from deeplearning4j_tpu.common.resilience import (FaultInjector,
                                                      RetryBudget,
                                                      RetryPolicy)
    from deeplearning4j_tpu.obs.fleet import merge_traces
    from deeplearning4j_tpu.serving import (CHAOS_ACTIONS, DecodeSizeMix,
                                            FleetManager, PoisonPillError,
                                            RemoteReplica,
                                            ServerClosedError,
                                            ServingMetrics,
                                            StaleEpochError,
                                            build_chaos_schedule,
                                            build_schedule, run_load)
    injector = FaultInjector()
    # cascade: a generous shared budget — wire resends, reconnects and
    # failover replays all spend from it; sized so the seeded storm
    # never exhausts it (exhaustion is a unit-tested verdict, the sweep
    # pins that the machinery runs end-to-end without changing outcomes)
    budget = RetryBudget(capacity=512, initial=512) if cascade else None
    retry = RetryPolicy(max_retries=4, base_delay=0.05, max_delay=0.5,
                        jitter=0.0, budget=budget)
    tmpdir = tempfile.mkdtemp(prefix="fleet_chaos_")
    jpath = os.path.join(tmpdir, "fleet.journal")
    here = os.path.abspath(__file__)
    procs, trace_files = {}, {}

    def launch(name):
        port_file = os.path.join(tmpdir, f"{name}.port")
        trace_out = (os.path.join(tmpdir, f"{name}.trace.json")
                     if trace else None)
        cmd = [sys.executable, here, "--replica-serve",
               "--instance", name, "--port-file", port_file,
               "--identity-file", os.path.join(tmpdir, f"{name}.json"),
               "--slo-ms", str(slo_ms), "--slots", str(slots)]
        if trace_out:
            cmd += ["--trace-out", trace_out]
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        procs[name] = subprocess.Popen(cmd, env=env)
        trace_files[name] = trace_out
        return port_file

    def wait_port(name, port_file, timeout=300.0):
        t0 = time.monotonic()
        while time.monotonic() - t0 < timeout:
            if os.path.exists(port_file):
                return int(open(port_file).read().strip())
            if procs[name].poll() is not None:
                raise RuntimeError(
                    f"replica process {name} exited rc="
                    f"{procs[name].returncode} before binding")
            time.sleep(0.05)
        raise TimeoutError(f"replica {name} never published its port")

    names = [f"i{k}" for k in range(int(n_replicas))]
    ports = {n: launch(n) for n in names}

    spawn_calls = {"n": 0}          # every factory invocation
    spawn_fail_arm = {"on": False}  # the chaos spawn_fail window

    def factory(name):
        spawn_calls["n"] += 1
        if spawn_fail_arm["on"]:
            raise RuntimeError(
                "chaos spawn_fail window: factory refused to spawn")
        port_file = ports.pop(name, None)
        if port_file is None:
            port_file = launch(name)        # backfill / crash respawn
        port = wait_port(name, port_file)
        return RemoteReplica("127.0.0.1", port, name=name,
                             retry_policy=retry, heartbeat_interval=0.1,
                             fault_injector=injector,
                             process=procs[name])

    def redial(name, ident):
        # recovery re-dial: NO name= — the identity check must read the
        # instance the replica CLAIMS in its HELLO, not our expectation
        return RemoteReplica(ident.get("host") or "127.0.0.1",
                             ident["port"], retry_policy=retry,
                             heartbeat_interval=0.1,
                             fault_injector=injector,
                             process=procs.get(name))

    def warmup(srv):
        for p in ([1, 2, 3, 4], list(range(1, 13))):
            srv.generate(p, 4, deadline_ms=600_000, timeout=300)

    if cascade:
        # the containment pool: poison + spawn_fail ride along with
        # wire severs and the guaranteed manager kill (replica_crash
        # stays out — the poison's own kills are the deaths this arm
        # measures). require= fills any action the draw missed, inside
        # the builder, so the digest still pins the timeline.
        schedule = build_chaos_schedule(
            duration_s=max(1.0, float(chaos_events)),
            n_events=max(int(chaos_events), 3), seed=seed,
            actions=("sever_submit", "sever_stream", "poison",
                     "spawn_fail", "manager_kill"),
            require=("poison", "spawn_fail", "manager_kill"))
    else:
        schedule = build_chaos_schedule(
            duration_s=max(1.0, float(chaos_events)),
            n_events=int(chaos_events), seed=seed,
            actions=("sever_submit", "sever_stream", "sever_heartbeat",
                     "replica_crash", "manager_kill"))
    mix = DecodeSizeMix(((0.8, (3, 12), (4, 24)),
                         (0.2, (8, 16), (24, 44))), vocab=96)
    prompts = [[1, 2, 3]] + [[4 + j, 5, 6] for j in range(5)]
    poison_prompt = [13, 13, 13]    # never among the reference prompts

    def kill_hook(prompt, replica_name):
        return list(prompt) == poison_prompt

    # cascade containment knobs: short infancy + backoff so the breaker
    # opens, probes, and closes inside the smoke budget; a journal
    # compaction threshold small enough that the chaos run's record
    # volume actually triggers a fold+rotate before the manager kill
    containment_kw = dict(
        kill_hook=kill_hook, retry_budget=budget,
        infant_mortality_s=0.4, breaker_backoff_s=0.3,
        journal_compact_bytes=768) if cascade else {}
    mgr = FleetManager(factory, n_replicas=n_replicas, warmup=warmup,
                       heartbeat_timeout=2.0, fault_injector=injector,
                       metrics=ServingMetrics(name="fleet"),
                       journal=jpath, **containment_kw)
    stale = None
    admitted = completed = failed = 0
    chaos_log = []
    recovery_rec = None
    poison_fired = False
    cascade_rec = {}

    def fault_batch(tag):
        # plant-then-drive: a planted sever only matters to traffic
        # that crosses the site, so every fault event drives the SAME
        # reference prompts through the disturbed fleet and pins them
        # bit-identical (dedup re-delivery, retry, and failover replay
        # are invisible under deterministic greedy) — or failed LOUDLY
        nonlocal admitted, completed, failed
        futs = [mgr.submit(p, 24, deadline_ms=600_000) for p in prompts]
        admitted += len(futs)
        results, resolved, loud = [], 0, 0
        for f in futs:
            try:
                results.append(list(f.result(300)))
                resolved += 1
            except (cf.TimeoutError, TimeoutError):
                results.append(None)        # the one unacceptable end
            except Exception:   # noqa: BLE001 — loud failure resolves
                results.append(None)
                resolved += 1
                loud += 1
        completed += resolved - loud
        failed += loud
        return {"tag": tag, "all_resolved": resolved == len(futs),
                "loud_failures": loud,
                "bit_identical": results == refs}
    try:
        mgr.start()
        # quiet-fleet references: THE streams every disturbed replay
        # must reproduce (fixed-seed weights ⇒ fleet-wide determinism)
        refs = [list(mgr.generate(p, 24, deadline_ms=600_000,
                                  timeout=300)) for p in prompts]
        slice_n = max(2, int(n_req) // max(1, schedule.n))
        for ev_i, ev in enumerate(schedule.events):
            # real arrivals between faults: one seeded schedule slice
            rate = rates[ev_i % len(rates)]
            sched = build_schedule(_process_for(process, rate), mix,
                                   slice_n, seed=seed + ev_i * 1000)
            pt = run_load(mgr, sched, metrics=None)
            admitted += pt["admitted"]
            completed += pt["completed"]
            failed += pt["failed"]
            action = ev["action"]
            rec = {"t": ev["t"], "action": action}
            if action == "manager_kill":
                pre_fv = mgr.fleet_view()
                pre_done = {n: pre_fv.flat(n).get("completed") or 0
                            for n in pre_fv.instances}
                stale, mgr = mgr, None
                # simulate the manager process dying mid-fleet: its
                # journal handle vanishes with it; its replica sockets
                # stay half-open (the zombie the fencing exists for)
                j, stale._journal = stale._journal, None
                if j is not None:
                    j.close()
                mgr = FleetManager.recover(
                    factory, jpath, redial=redial, identity_dir=tmpdir,
                    n_replicas=n_replicas, warmup=warmup,
                    heartbeat_timeout=2.0, fault_injector=injector,
                    metrics=ServingMetrics(name="fleet"),
                    **containment_kw)
                snap = mgr.fleet_snapshot()
                post_fv = mgr.fleet_view()
                monotone = all(
                    (post_fv.flat(n).get("completed") or 0)
                    >= pre_done.get(n, 0)
                    for n in post_fv.instances if n in pre_done)
                # fencing pin: the predecessor's next control op must
                # be refused with the TYPED error, not half-obeyed
                fenced = None
                victims = [n for n in stale.replicas
                           if n in mgr.replicas]
                if victims:
                    try:
                        stale.replica(victims[0]).drain(timeout=5.0)
                        fenced = False
                    except StaleEpochError:
                        fenced = True
                    except Exception as e:  # noqa: BLE001
                        fenced = f"wrong error: {type(e).__name__}"
                # the zombie's wire halves close LOCALLY only — a
                # STOP/KILL frame from it at live replicas is exactly
                # what the epoch fence forbids
                for n in list(stale.replicas):
                    try:
                        stale.replica(n)._shutdown_local(
                            ServerClosedError(
                                "superseded by recovered manager"),
                            dead=False)
                    except Exception:   # noqa: BLE001
                        pass
                stale._running = False
                recovery_rec = {
                    "epoch": mgr.epoch,
                    "replicas_adopted": snap["fleet_replicas_adopted"],
                    "fenced_op_refused": fenced,
                    "fenced_ops_counted": mgr.fleet_snapshot()[
                        "fleet_fenced_ops"],
                    "counters_monotone_across_restart": monotone,
                }
                if cascade:
                    # the quarantine is journaled: a successor built
                    # from the journal must keep shedding the convicted
                    # prompt at the door, NOT resurrect it onto the
                    # fresh fleet (where its decode would kill again)
                    inherited = None
                    if poison_fired:
                        try:
                            f = mgr.submit(poison_prompt, 12,
                                           deadline_ms=600_000)
                            admitted += 1
                            inherited = False
                            try:
                                f.result(300)
                                completed += 1
                            except Exception:   # noqa: BLE001
                                failed += 1
                        except PoisonPillError:
                            inherited = True
                    recovery_rec["quarantine_inherited"] = inherited
                    recovery_rec["breaker_state_inherited"] = \
                        mgr.breaker_state
                rec["recovery"] = recovery_rec
                rec.update(fault_batch("post_recovery"))
            elif action == "poison":
                # the poison pill: its decode kills the replica it
                # lands on (kill hook), its replay kills the next one,
                # the second death convicts it — PoisonPillError on the
                # outer future, fingerprint quarantined + journaled
                pre_dead = mgr.fleet_snapshot()["fleet_replica_dead"]
                pf = mgr.submit(poison_prompt, 12, deadline_ms=600_000)
                admitted += 1
                try:
                    pf.result(300)
                    verdict = "completed"   # unacceptable — recorded
                    completed += 1
                except PoisonPillError:
                    verdict = "poison_pill"
                    failed += 1
                except Exception as e:      # noqa: BLE001
                    verdict = f"wrong error: {type(e).__name__}"
                    failed += 1
                # a re-submission of the convicted prompt sheds at the
                # door — it must never reach (and kill) a third replica
                reshed = None
                try:
                    f2 = mgr.submit(poison_prompt, 12,
                                    deadline_ms=600_000)
                    admitted += 1
                    reshed = False
                    try:
                        f2.result(300)
                        completed += 1
                    except Exception:       # noqa: BLE001
                        failed += 1
                except PoisonPillError:
                    reshed = True
                mgr.control_tick()  # backfill past the poison's kills
                poison_fired = True
                fsnap = mgr.fleet_snapshot()
                rec["poison"] = {
                    "verdict": verdict,
                    "deaths": fsnap["fleet_replica_dead"] - pre_dead,
                    "resubmission_shed": reshed,
                    "quarantined_counter":
                        fsnap["fleet_requests_quarantined"]}
                rec.update(fault_batch("post_poison"))
            elif action == "spawn_fail":
                # factory-failure window: crash one replica so the
                # control loop must backfill, with every spawn attempt
                # refused — K consecutive strikes OPEN the breaker and
                # the fleet serves degraded on its survivors instead of
                # crash-looping one spawn per tick
                attempts0 = spawn_calls["n"]
                spawn_fail_arm["on"] = True
                victim = mgr.replicas[0]
                mgr._crash(victim, reason="chaos: spawn_fail window")
                mgr.control_tick()  # strikes accumulate; breaker opens
                opened = mgr.breaker_state
                mgr.control_tick()  # OPEN: these ticks may not spawn
                mgr.control_tick()
                attempts = spawn_calls["n"] - attempts0
                rec["breaker"] = {
                    "state_after_window": opened,
                    "spawn_attempts_in_window": attempts,
                    "bounded": attempts <= mgr.breaker_strikes}
                rec.update(fault_batch("degraded"))
                # heal: the window closes, the half-open probe spawns
                # after the backoff, survives infancy, and the breaker
                # closes with the fleet restored to full strength
                spawn_fail_arm["on"] = False
                deadline = time.monotonic() + 60.0
                while (mgr.breaker_state != "closed"
                       or mgr.n_alive() < n_replicas) \
                        and time.monotonic() < deadline:
                    mgr.control_tick()
                    time.sleep(0.05)
                fsnap = mgr.fleet_snapshot()
                rec["breaker"]["recovered_state"] = mgr.breaker_state
                rec["breaker"]["n_alive_after"] = mgr.n_alive()
                rec["breaker"]["breaker_open_total"] = \
                    fsnap["fleet_breaker_open_total"]
                rec["breaker"]["degraded_mode_ticks"] = \
                    fsnap["fleet_degraded_mode_ticks"]
            elif action == "replica_crash":
                injector.plan("fleet.replica",
                              on_call=injector.calls("fleet.replica"),
                              sever=True, exc=None)
                mgr.control_tick()      # fires the crash + backfills
                rec["n_alive_after"] = mgr.n_alive()
                rec.update(fault_batch("post_crash"))
            else:
                site = CHAOS_ACTIONS[action]
                injector.plan(site, on_call=injector.calls(site),
                              sever=True, exc=None)
                rec["site"] = site
                rec.update(fault_batch(action))
            chaos_log.append(rec)
        # the closing wave: the recovered fleet, quiet again, must
        # still serve the reference streams bit-for-bit
        chaos_log.append(fault_batch("final_quiet"))
        final_snap = mgr.fleet_snapshot()
        snaps = {n: mgr.replica(n).metrics.snapshot()
                 for n in mgr.replicas}
        pids = {n: procs[n].pid for n in procs}
        if cascade:
            # journal facts read BEFORE the tmpdir vanishes: a
            # `snapshot` record means compact() folded + rotated the
            # file mid-run (the compaction threshold is set low enough
            # that the chaos run's record volume crosses it)
            from deeplearning4j_tpu.serving import replay_journal
            cascade_rec = {
                "journal_bytes": os.path.getsize(jpath),
                "journal_compacted": any(
                    r.get("kind") == "snapshot"
                    for r in replay_journal(jpath))}
    finally:
        if mgr is not None:
            mgr.stop(timeout=120)
        if stale is not None:
            stale._running = False
        for p in procs.values():        # belt and braces
            if p.poll() is None:
                p.terminate()
        for p in procs.values():
            try:
                p.wait(timeout=30)
            except Exception:   # noqa: BLE001
                p.kill()
    merged = None
    if trace:
        saved, tnames = [], []
        for n, path in trace_files.items():
            if path and os.path.exists(path):
                with open(path) as fh:
                    saved.append(json.load(fh))
                tnames.append(n)
        if saved:
            merged = merge_traces(saved, names=tnames)
    shutil.rmtree(tmpdir, ignore_errors=True)
    body = {"server": "fleet_chaos", "n_replicas": int(n_replicas),
            "process": process,
            "config": f"journaled FleetManager over {n_replicas} "
                      f"replica PROCESSES, slots={slots}, seeded chaos "
                      f"schedule ({schedule.n} events, digest "
                      f"{schedule.digest()[:12]}), one manager "
                      f"kill+recover, admission deadline={slo_ms:g}ms"
                      + (", CASCADE containment arm (poison + "
                         "spawn_fail + shared retry budget)"
                         if cascade else ""),
            "unit": "resolved futures under chaos",
            "chaos": {"seed": seed, "n_events": schedule.n,
                      "digest": schedule.digest(),
                      "events": schedule.events, "log": chaos_log},
            "accounting": {"admitted": admitted, "completed": completed,
                           "failed": failed,
                           "balanced": admitted == completed + failed},
            "recovery": recovery_rec,
            "fleet": final_snap,
            "replica_pids": pids}
    if cascade:
        body["cascade"] = dict(
            cascade_rec,
            poison_prompt=poison_prompt,
            spawn_attempts_total=spawn_calls["n"],
            retry_budget={
                "capacity": budget.capacity,
                "tokens_remaining": budget.tokens,
                "denied": budget.denied})
    return body, snaps, merged


def sweep_microbatch(rates, n_req=96, slo_ms=50.0, seed=0,
                     process="poisson", tracer=None):
    """Rate ladder over the InferenceServer (requests/s domain)."""
    import numpy as np

    from deeplearning4j_tpu.serving import (InferenceServer,
                                            InferenceSizeMix,
                                            ServingMetrics,
                                            build_schedule, run_load)
    net = _mlp()
    metrics = ServingMetrics(slo_target_ms=slo_ms)
    srv = InferenceServer(net, max_batch=8, max_wait_ms=2.0,
                          max_queue=1024, metrics=metrics,
                          tracer=tracer).start()
    mix = InferenceSizeMix(32)
    try:
        # compile every bucket program off the clock
        rng = np.random.default_rng(1)
        xs = rng.standard_normal((8, 32)).astype(np.float32)
        for burst in (1, 4, 8):
            for f in [srv.submit(x) for x in xs[:burst]]:
                f.result(120)
        curve = []
        for i, rate in enumerate(rates):
            sched = build_schedule(_process_for(process, rate), mix,
                                   n_req, seed=seed + i)
            pt = run_load(srv, sched)
            pt["offered_rate_target"] = rate
            pt["_offered"] = pt["schedule"]["offered_rps"]
            pt["_achieved"] = pt["requests_per_sec"]
            curve.append(pt)
        snap = metrics.snapshot()
    finally:
        srv.stop(timeout=120)
    return {"server": "microbatch", "process": process,
            "config": "MLP 32->64->10, max_batch=8 max_wait=2ms, "
                      f"{n_req} reqs/rate, slo={slo_ms:g}ms",
            "unit": "requests/sec",
            "curve": curve, "knee": _knee(curve)}, snap


def _goodput(pt):
    slo = pt.get("slo") or {}
    return slo.get("goodput_tokens_per_sec") or 0.0


# measurement slack for the monotonicity verdict: goodput at the next
# rung may dip this fraction below the previous rung before the curve
# counts as collapsed. The band is wide because it must separate
# CONTROL failure from MACHINE weather: on the shared-CPU measurement
# host, back-to-back identical baseline runs at one rate vary by >2x
# (measured), so a tight slack would assert the scheduler's mood, not
# the controller's. The thing being excluded is unambiguous — the
# uncontrolled baseline drops 4-15x past the knee and fails this
# verdict in every capture; the controlled arm's worst observed
# successive-rung ratio is 0.64.
MONOTONE_SLACK = 0.6


def overload_compare(baseline, controlled, dec_base=None, dec_ctrl=None):
    """The PR 9 acceptance record: the SAME rate ladder through an
    uncontrolled server (the PR 7 baseline semantics) and one with
    chunked prefill + deadline-aware admission. Columns per rate:
    goodput-under-SLO and TTFT p99 for both arms plus the controlled
    arm's shed-reason breakdown; verdicts: controlled goodput
    monotone-nondecreasing past the knee (vs the baseline collapse) and
    TTFT p99 bounded. `dec_base`/`dec_ctrl` are optional span
    decompositions — the sched_gap fraction is chunking's direct
    before/after metric."""
    rows = []
    for b, c in zip(baseline["curve"], controlled["curve"]):
        rows.append({
            "offered_rps": b["offered_rate_target"],
            "goodput_baseline": _goodput(b),
            "goodput_controlled": _goodput(c),
            "ttft_ms_p99_baseline": b.get("ttft_ms_p99"),
            "ttft_ms_p99_controlled": c.get("ttft_ms_p99"),
            "sheds_controlled": c.get("sheds")})
    knee_rate = baseline["knee"]["knee_offered_rate"]
    g_all = [r["goodput_controlled"] for r in rows]
    # past-knee slice: the knee point itself plus everything beyond
    start = next((i for i, r in enumerate(rows)
                  if knee_rate is None or r["offered_rps"] >= knee_rate),
                 0)
    g = g_all[start:]
    monotone = all(g[i + 1] >= MONOTONE_SLACK * g[i]
                   for i in range(len(g) - 1))
    gb = [r["goodput_baseline"] for r in rows[start:]]
    collapse = (round(max(gb) / min(gb), 2)
                if gb and min(gb) > 0 else None)
    ttft_c = [r["ttft_ms_p99_controlled"] for r in rows
              if r["ttft_ms_p99_controlled"] is not None]
    ttft_b = [r["ttft_ms_p99_baseline"] for r in rows
              if r["ttft_ms_p99_baseline"] is not None]
    out = {"server": "decode_overload_ab",
           "knee_offered_rate": knee_rate,
           "rows": rows,
           "controlled_goodput_monotone_past_knee": monotone,
           "monotone_slack": MONOTONE_SLACK,
           "baseline_goodput_collapse_x": collapse,
           "ttft_ms_p99_max": {"baseline": max(ttft_b, default=None),
                               "controlled": max(ttft_c, default=None)}}
    if dec_base and dec_ctrl:
        out["sched_gap_fraction"] = {
            "baseline": (dec_base.get("fractions") or {}).get(
                "sched_gap_ms"),
            "controlled": (dec_ctrl.get("fractions") or {}).get(
                "sched_gap_ms")}
    return out


def run_sweep(server="both", rates=(50, 100, 200, 400, 800),
              process="poisson", n_req=64, slo_ms=150.0, seed=0,
              trace=True, report_path=None, paged=False,
              chunked_prefill=None, admission=None, overload_ab=False,
              speculate_k=None, preempt=False, fused_serve=None,
              fleet=0,
              fleet_obs_per_rate=6, fleet_slice_s=0.25,
              fleet_control=False, fleet_injector=None,
              fleet_min=None, fleet_max=None, fleet_procs=0,
              chaos=False, chaos_events=5, cascade=False,
              affinity=False):
    """Drive the sweep(s) and (optionally) write the combined
    obs_report (JSON + text + Chrome trace). Returns the results list.
    The tier-1 smoke test calls this with tiny parameters (and once
    with paged=True so CI exercises the block-gated admission path).
    `overload_ab=True` replays the decode ladder through BOTH an
    uncontrolled baseline and a chunked+admission arm and appends the
    comparison record (goodput monotonicity past the knee — the PR 9
    acceptance pin). `fleet=N` (N >= 2) replaces the single decode
    server with N round-robin replicas + the fleet observability plane
    (sweep_fleet): the report's trace becomes the clock-anchor-MERGED
    multi-instance trace (written as `<report>.trace.merged.json`) and
    every rate rung carries the autoscale decision sequence."""
    from deeplearning4j_tpu.obs import Tracer, decompose
    fleet = int(fleet or 0)
    fleet_procs = int(fleet_procs or 0)
    if fleet_procs == 1:
        raise ValueError("--fleet-procs needs N >= 2 replica processes "
                         "(a fleet of one is the plain decode sweep — "
                         "drop the flag)")
    if fleet_procs and (fleet or fleet_control or overload_ab):
        raise ValueError("--fleet-procs is its own scenario: drop "
                         "--fleet/--fleet-control/--overload-ab")
    if affinity and (fleet or fleet_control or overload_ab or chaos):
        raise ValueError("--affinity is its own scenario (solo vs "
                         "affinity vs least_backlog on one shared-"
                         "prefix workload): drop --fleet/"
                         "--fleet-control/--overload-ab/--chaos")
    if affinity and server not in ("decode", "both"):
        raise ValueError("--affinity needs --server decode (or both): "
                         "the prefix-affinity arm drives paged DECODE "
                         "replicas")
    if chaos and fleet_procs < 2:
        raise ValueError("--chaos needs --fleet-procs N (>= 2): the "
                         "chaos schedule kills and recovers the "
                         "manager of a replica-PROCESS fleet — "
                         "silently running without it would discard "
                         "the flag")
    if cascade and not chaos:
        raise ValueError("--cascade extends the --chaos schedule with "
                         "poison + spawn_fail: add --chaos (and "
                         "--fleet-procs N >= 3)")
    if cascade and fleet_procs < 3:
        raise ValueError("--cascade needs --fleet-procs N (>= 3): the "
                         "poison pill kills TWO replicas before it is "
                         "convicted, and a survivor must keep serving "
                         "the co-victims it failed over")
    if fleet_procs and server not in ("decode", "both"):
        raise ValueError("--fleet-procs needs --server decode (or "
                         "both): the wire fleet drives DECODE replica "
                         "processes")
    if fleet == 1:
        raise ValueError("--fleet needs N >= 2 replicas (a fleet of "
                         "one is the plain decode sweep — drop the "
                         "flag)")
    if fleet_control and fleet < 2:
        raise ValueError("--fleet-control needs --fleet N (>= 2): the "
                         "closed loop drives a replica FLEET")
    fleet_mode = fleet >= 2 and server in ("decode", "both")
    if fleet_control and not fleet_mode:
        raise ValueError("--fleet-control needs --server decode (or "
                         "both): the closed loop drives DECODE "
                         "replicas — silently running the plain "
                         f"{server!r} ladder would discard the flag")
    if fleet_mode and overload_ab:
        raise ValueError("--fleet and --overload-ab are mutually "
                         "exclusive: the overload A/B compares one "
                         "controlled server against one baseline — "
                         "run them as separate sweeps")
    tracer = (Tracer(capacity=1 << 16, enabled=True)
              if trace and not (fleet_mode or fleet_procs or affinity)
              else None)
    fleet_trace = None
    results, snaps = [], {}
    if affinity:
        body, inst_snaps, fleet_trace = sweep_fleet_affinity(
            rates, n_replicas=3, n_req=n_req, slo_ms=slo_ms, seed=seed,
            process=process, trace=trace, procs=fleet_procs,
            obs_per_rate=fleet_obs_per_rate, slice_s=fleet_slice_s)
        results.append(body)
        snaps.update({f"fleet_{n}": s for n, s in inst_snaps.items()})
    elif fleet_procs >= 2 and chaos:
        body, inst_snaps, fleet_trace = sweep_fleet_chaos(
            rates, n_replicas=fleet_procs, n_req=n_req, slo_ms=slo_ms,
            seed=seed, process=process, trace=trace,
            chaos_events=chaos_events, cascade=cascade)
        results.append(body)
        snaps.update({f"fleet_{n}": s for n, s in inst_snaps.items()})
    elif fleet_procs >= 2:
        body, inst_snaps, fleet_trace = sweep_fleet_procs(
            rates, n_replicas=fleet_procs, n_req=n_req, slo_ms=slo_ms,
            seed=seed, process=process, trace=trace, paged=paged,
            obs_per_rate=fleet_obs_per_rate, slice_s=fleet_slice_s,
            fault_injector=fleet_injector)
        results.append(body)
        snaps.update({f"fleet_{n}": s for n, s in inst_snaps.items()})
    elif fleet_mode and fleet_control:
        body, inst_snaps, fleet_trace = sweep_fleet_control(
            rates, n_replicas=fleet, n_req=n_req, slo_ms=slo_ms,
            seed=seed, process=process, trace=trace,
            obs_per_rate=fleet_obs_per_rate, slice_s=fleet_slice_s,
            fault_injector=fleet_injector, min_replicas=fleet_min,
            max_replicas=fleet_max)
        results.append(body)
        snaps.update({f"fleet_{n}": s for n, s in inst_snaps.items()})
    elif fleet_mode:
        body, inst_snaps, fleet_trace = sweep_fleet(
            rates, n_replicas=fleet, n_req=n_req, slo_ms=slo_ms,
            seed=seed, process=process, trace=trace,
            obs_per_rate=fleet_obs_per_rate, slice_s=fleet_slice_s)
        results.append(body)
        snaps.update({f"fleet_{n}": s for n, s in inst_snaps.items()})
    elif overload_ab and server in ("decode", "both"):
        # EQUAL OFFERED DURATION per rung, both arms on identical
        # schedules: requests scale with rate (~1.5 s of traffic each),
        # because at a fixed count higher rates compress the arrival
        # window and shrink the in-SLO-completable work — absolute
        # goodput would decline past the knee for ANY controller. The
        # window is long enough that the admission loop's feedback
        # (bias, hysteresis, saturated-capacity) reaches equilibrium
        # inside each rung instead of measuring its transient.
        n_list = [min(max(24, int(r * 1.5)), 1500) for r in rates]
        print(json.dumps({"overload_ab_requests_per_rung": n_list,
                          "note": "--requests is overridden: equal "
                                  "offered duration per rung"}),
              file=sys.stderr)
        body_b, snap_b = sweep_decode(rates, n_req=n_list,
                                      slo_ms=slo_ms,
                                      seed=seed, process=process,
                                      tracer=tracer, paged=paged)
        tracer_c = Tracer(capacity=1 << 16, enabled=True) if trace \
            else None
        body_c, snap_c = sweep_decode(
            rates, n_req=n_list, slo_ms=slo_ms, seed=seed,
            process=process, tracer=tracer_c, paged=paged,
            chunked_prefill=(chunked_prefill or 8), admission=True)
        cmp_rec = overload_compare(
            body_b, body_c,
            decompose(tracer) if tracer else None,
            decompose(tracer_c) if tracer_c else None)
        results.extend([body_b, body_c, cmp_rec])
        snaps["decode_baseline"] = snap_b
        snaps["decode_controlled"] = snap_c
    elif server in ("decode", "both"):
        body, snap = sweep_decode(rates, n_req=n_req, slo_ms=slo_ms,
                                  seed=seed, process=process,
                                  tracer=tracer, paged=paged,
                                  chunked_prefill=chunked_prefill,
                                  admission=admission,
                                  speculate_k=speculate_k,
                                  preempt=preempt,
                                  fused_serve=fused_serve)
        results.append(body)
        snaps["decode"] = snap
    if server in ("microbatch", "both"):
        # the micro-batch rates ride the same ladder; its own tracer
        # would collide with the decode server's req-<id> lanes, so the
        # shared tracer is decode-only and decomposition covers decode
        mb_rates = tuple(max(20, r // 2) for r in rates)
        body, snap = sweep_microbatch(mb_rates, n_req=n_req,
                                      slo_ms=min(slo_ms, 50.0),
                                      seed=seed, process=process)
        results.append(body)
        snaps["microbatch"] = snap
    if report_path:
        # obs_report lives next to this file, not under the repo-root
        # entry this module inserts — `python -m tools.load_sweep` or an
        # importing test must not lose a finished sweep at report time
        tools_dir = os.path.dirname(os.path.abspath(__file__))
        if tools_dir not in sys.path:
            sys.path.insert(0, tools_dir)
        from obs_report import build_report, format_report
        report = build_report(
            spans=fleet_trace if fleet_trace is not None else tracer,
            metrics=snaps)
        report["sweep"] = results
        with open(report_path + ".json", "w") as fh:
            json.dump(report, fh)
        with open(report_path + ".txt", "w") as fh:
            fh.write(format_report(report) + "\n")
            for r in results:
                fh.write(f"\n== sweep: {r['server']} "
                         f"({r.get('process', 'comparison')}) ==\n")
                for pt in r.get("curve") or r.get("rows") or ():
                    fh.write(json.dumps(pt) + "\n")
                if "knee" in r:
                    fh.write(json.dumps(r["knee"]) + "\n")
        if fleet_trace is not None:
            # the fleet's one trace artifact IS the merged trace: every
            # replica's process group on one clock-anchored timeline
            with open(report_path + ".trace.merged.json", "w") as fh:
                json.dump(fleet_trace, fh)
        if tracer is not None:
            tracer.save(report_path + ".trace.json")
    return results


def main():
    if "--replica-serve" in sys.argv:
        # child-process mode: this invocation IS one wire replica
        return _replica_serve_main(sys.argv[1:])
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--server", default="both",
                    choices=("decode", "microbatch", "both"))
    ap.add_argument("--rates", default="50,100,200,400,800",
                    help="comma-separated offered rates (requests/sec; "
                         "concurrency levels for --process closed)")
    ap.add_argument("--process", default="poisson",
                    choices=("poisson", "onoff", "closed"))
    ap.add_argument("--requests", type=int, default=64,
                    help="requests per sweep point")
    ap.add_argument("--slo-ms", type=float, default=150.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--report", default=None,
                    help="write obs_report JSON/text/trace under this "
                         "path prefix")
    ap.add_argument("--no-trace", action="store_true",
                    help="disable span tracing (no decomposition in "
                         "the report)")
    ap.add_argument("--paged", action="store_true",
                    help="decode server uses the paged block-table KV "
                         "cache (equal-bytes arena) instead of fixed "
                         "slots")
    ap.add_argument("--speculate", type=int, default=None, metavar="K",
                    help="K-wide n-gram speculative decode on the "
                         "decode server (composes with --paged: the "
                         "block-table verify program)")
    ap.add_argument("--fused-serve", type=int, default=None,
                    metavar="K",
                    help="scan K decode iterations into one device "
                         "dispatch on the decode server (composes "
                         "with --paged; excludes --speculate — the "
                         "server refuses the combination)")
    ap.add_argument("--fleet", type=int, default=0, metavar="N",
                    help="drive N in-process decode replicas behind a "
                         "round-robin splitter (named instances, "
                         "federated metrics, one AutoscaleSignal fed "
                         "per schedule slice, clock-anchor-merged "
                         "trace) instead of one decode server")
    ap.add_argument("--fleet-control", action="store_true",
                    help="CLOSED-LOOP fleet arm (needs --fleet N): a "
                         "FleetManager drives replica count — one "
                         "control tick per schedule slice ACTS on the "
                         "AutoscaleSignal (scale_up spawns a warmed "
                         "replica, scale_down drains one with live-"
                         "request migration); the record pins goodput "
                         "recovery after the spawn and the quiet-tail "
                         "return to min replicas")
    ap.add_argument("--fleet-min", type=int, default=None,
                    help="fleet-control floor (default: the initial N)")
    ap.add_argument("--fleet-max", type=int, default=None,
                    help="fleet-control ceiling (default: N + 4)")
    ap.add_argument("--fleet-procs", type=int, default=0, metavar="N",
                    help="drive N replica PROCESSES behind the serving "
                         "wire (serving/wire.py): each replica is a "
                         "real child process serving the socket "
                         "protocol, routed by the FleetManager; after "
                         "the rate rungs one socket sever is injected "
                         "mid-stream and the record pins zero lost "
                         "requests + bit-identical streams + the "
                         "merged trace covering every replica pid")
    ap.add_argument("--chaos", action="store_true",
                    help="DURABLE-CONTROL-PLANE arm (needs "
                         "--fleet-procs N): journal every fleet state "
                         "transition, fire a seeded chaos schedule "
                         "(socket severs, a replica crash, one MANAGER "
                         "kill) between load slices, recover the "
                         "manager from the journal with replica "
                         "re-adoption, and pin: every admitted future "
                         "resolves (bit-identical or loudly failed), "
                         "admitted == completed + failed, the stale "
                         "manager's next control op is epoch-fenced")
    ap.add_argument("--chaos-events", type=int, default=5, metavar="E",
                    help="chaos schedule length (>= 1; one is always "
                         "a manager kill)")
    ap.add_argument("--affinity", action="store_true",
                    help="PREFIX-AFFINITY arm: a seeded shared-system-"
                         "prompt workload (SharedPrefixMix) over 3 "
                         "paged replicas (or --fleet-procs N replica "
                         "PROCESSES) three ways — solo reference, "
                         "consistent-hash affinity routing with the "
                         "fleet prefix tier (cross-replica block "
                         "pulls), least-backlog baseline — recording "
                         "fleet hit rate vs solo, pull counts/bytes, "
                         "goodput vs baseline, and the zero-added-"
                         "dispatch A/B for the no-pull path")
    ap.add_argument("--cascade", action="store_true",
                    help="BLAST-RADIUS-CONTAINMENT arm (needs --chaos "
                         "and --fleet-procs N >= 3): the schedule adds "
                         "a poison request (its decode kills the "
                         "replica it lands on; two kills convict it — "
                         "typed PoisonPillError + journaled "
                         "quarantine) and a spawn_fail factory window "
                         "(the spawn circuit breaker opens after K "
                         "strikes; the fleet serves degraded instead "
                         "of crash-looping), with a shared fleet-wide "
                         "retry budget gating resends and replays")
    ap.add_argument("--preempt", action="store_true",
                    help="durable-KV preemption (implies --paged): the "
                         "mix's long tail submits as a spillable batch "
                         "class, short turns as interactive — batch "
                         "slots spill to host when interactive work "
                         "is blocked on KV blocks")
    ap.add_argument("--chunked-prefill", type=int, default=None,
                    metavar="C",
                    help="slice prompts into C-row prefill chunks "
                         "(head-of-line surgery; >= 2)")
    ap.add_argument("--admission", action="store_true",
                    help="deadline-aware admission: shed predicted "
                         "deadline misses at enqueue (requests get the "
                         "SLO as their deadline)")
    ap.add_argument("--overload-ab", action="store_true",
                    help="run the decode ladder through BOTH a baseline "
                         "and a chunked+admission arm and append the "
                         "goodput-monotonicity comparison record. "
                         "OVERRIDES --requests: each rung offers ~1.5 s "
                         "of traffic (requests scale with rate) so "
                         "goodput is comparable across rungs")
    args = ap.parse_args()
    rates = tuple(float(r) for r in args.rates.split(","))
    t0 = time.perf_counter()
    results = run_sweep(server=args.server, rates=rates,
                        process=args.process, n_req=args.requests,
                        slo_ms=args.slo_ms, seed=args.seed,
                        trace=not args.no_trace,
                        report_path=args.report, paged=args.paged,
                        chunked_prefill=args.chunked_prefill,
                        admission=args.admission,
                        overload_ab=args.overload_ab,
                        speculate_k=args.speculate,
                        fused_serve=args.fused_serve,
                        preempt=args.preempt, fleet=args.fleet,
                        fleet_control=args.fleet_control,
                        fleet_min=args.fleet_min,
                        fleet_max=args.fleet_max,
                        fleet_procs=args.fleet_procs,
                        chaos=args.chaos,
                        chaos_events=args.chaos_events,
                        cascade=args.cascade,
                        affinity=args.affinity)
    for r in results:
        print(json.dumps(r))
    print(json.dumps({"elapsed_s": fmt(time.perf_counter() - t0, 1),
                      "report": args.report and args.report
                      + ".{json,txt,trace.json}"}))


if __name__ == "__main__":
    main()
