"""Throughput–latency sweep: offered rate -> what the servers deliver.

The traffic-harness headline tool: drive a REAL server
(`ContinuousDecodeServer` and/or `InferenceServer`) with seeded arrival
schedules (`serving/loadgen.py`) at a ladder of offered rates, and emit
the curve every serving claim should be judged on:

  offered rate -> achieved tokens/s (requests/s for the micro-batch
  server), request p50/p99, TTFT p99, inter-token p99, SLO attainment,
  goodput-under-SLO, shed counts, submit-lateness (open-loop fidelity)

plus the SATURATION KNEE — the highest offered rate the server still
sustains (achieved >= 90% of offered). Below the knee latency is flat;
past it the queue grows without bound and p99/sheds are the story. The
combined `tools/obs_report.py` view (host spans + span-derived latency
decomposition + per-rate metrics) is written with `--report`.

Run (CPU backend, no chip needed):

    JAX_PLATFORMS=cpu python tools/load_sweep.py \
        [--server both] [--rates 50,100,200,400,800] \
        [--process poisson|onoff|closed] [--requests 64] \
        [--slo-ms 150] [--seed 0] [--report /tmp/sweep] [--no-trace]

`--process onoff` keeps the same MEAN rate but bursts at 2x with a 50%
duty cycle (the p99 stressor); `--process closed` reinterprets each
"rate" as a fixed concurrency (the coordinated-omission contrast).
`bench.py`'s `load_sweep` config pins one sweep point per record;
tests/test_loadgen.py runs the smoke version in tier-1 and CI uploads
its report JSON.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from deeplearning4j_tpu.obs.registry import fmt  # noqa: E402

KNEE_THRESH = 0.9


def _lm():
    import jax.numpy as jnp

    from deeplearning4j_tpu.models.zoo.transformer import TransformerLM
    return TransformerLM(96, d_model=32, n_heads=2, n_layers=2,
                         max_len=64, seed=5, dtype=jnp.float32)


def _mlp():
    from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    conf = (NeuralNetConfiguration.Builder().seed(7)
            .updater("adam").learning_rate(0.01).list()
            .layer(0, DenseLayer(n_out=64, activation="relu"))
            .layer(1, OutputLayer(n_out=10, activation="softmax",
                                  loss_function="mcxent"))
            .set_input_type(InputType.feed_forward(32))
            .build())
    return MultiLayerNetwork(conf).init()


def _process_for(process, rate):
    """Map one sweep 'rate' onto an arrival process. onoff keeps the
    same MEAN rate but bursts at 2x with a 50% duty cycle; closed
    reinterprets rate as a concurrency level."""
    from deeplearning4j_tpu.serving import (ClosedLoop, OnOffProcess,
                                            PoissonProcess)
    if process == "poisson":
        return PoissonProcess(rate)
    if process == "onoff":
        return OnOffProcess(2.0 * rate, on_s=0.5, off_s=0.5)
    if process == "closed":
        return ClosedLoop(max(1, int(rate)))
    raise ValueError(f"unknown process {process!r}")


def _knee(curve):
    """Saturation knee over annotated points (each carries `_offered` /
    `_achieved`): the last point before the first unsustained one."""
    knee = first_bad = None
    for pt in curve:
        off, ach = pt.pop("_offered", None), pt.pop("_achieved", None)
        if not off or ach is None:
            continue
        pt["sustained_ratio"] = round(ach / off, 3)
        if first_bad is None:
            if ach / off >= KNEE_THRESH:
                knee = pt
            else:
                first_bad = pt
    return {
        "criterion": f"achieved >= {KNEE_THRESH:g} x offered",
        "knee_offered_rate": knee and knee["offered_rate_target"],
        "knee_achieved": knee and (knee.get("tokens_per_sec")
                                   or knee.get("requests_per_sec")),
        "first_unsustained_rate": (
            first_bad and first_bad["offered_rate_target"]),
    }


def sweep_decode(rates, n_req=64, slo_ms=150.0, seed=0,
                 process="poisson", tracer=None, lm=None, slots=4,
                 paged=False, block_size=8):
    """Rate ladder over the ContinuousDecodeServer. One server serves
    every rate (compile once); per-point accounting is delta-based
    (loadgen baselines at entry), so points never contaminate each
    other. Offered/achieved compare in TOKENS/s — the decode server's
    capacity is token throughput, not request admission.

    `paged=True` swaps in the block-table KV cache (serving/kvpool.py)
    at the default equal-bytes arena: the same sweep drives the
    block-gated admission path instead of the slot-gated one — the
    tier-1 smoke sweep runs one paged rate so CI exercises it."""
    from deeplearning4j_tpu.serving import (ContinuousDecodeServer,
                                            DecodeSizeMix,
                                            ServingMetrics,
                                            build_schedule, run_load)
    lm = lm if lm is not None else _lm()
    metrics = ServingMetrics(slo_target_ms=slo_ms)
    srv = ContinuousDecodeServer(
        lm, slots=slots, prompt_buckets=(8, 16), max_queue=1024,
        metrics=metrics, tracer=tracer, paged=paged,
        block_size=block_size).start()
    # mostly short chat turns + a tail of long generations — the mixed-
    # length shape continuous batching exists for
    mix = DecodeSizeMix(((0.8, (3, 12), (4, 24)),
                         (0.2, (8, 16), (24, 44))), vocab=96)
    try:
        # compile both prompt buckets + the decode step off the clock
        for p in ([1, 2, 3, 4], list(range(1, 13))):
            srv.generate(p, 4, timeout=300)
        curve = []
        for i, rate in enumerate(rates):
            sched = build_schedule(_process_for(process, rate), mix,
                                   n_req, seed=seed + i)
            pt = run_load(srv, sched)
            pt["offered_rate_target"] = rate
            pt["_offered"] = pt["schedule"]["offered_tokens_per_sec"]
            pt["_achieved"] = pt["tokens_per_sec"]
            curve.append(pt)
        snap = metrics.snapshot()
    finally:
        srv.stop(timeout=120)
    # describe the model actually measured (bench.py passes bigger ones)
    d_model = int(lm.aux["tok"].shape[1])
    cache = (f"paged bs={block_size}" if paged else "fixed-slot")
    return {"server": "decode", "process": process, "paged": bool(paged),
            "config": f"TransformerLM L={len(lm.blocks)} d={d_model} "
                      f"slots={slots} cache={cache}, mix 80% "
                      f"short(p3-11/n4-23) + 20% long(p8-15/n24-43), "
                      f"{n_req} reqs/rate, slo={slo_ms:g}ms",
            "unit": "generated tokens/sec",
            "curve": curve, "knee": _knee(curve)}, snap


def sweep_microbatch(rates, n_req=96, slo_ms=50.0, seed=0,
                     process="poisson", tracer=None):
    """Rate ladder over the InferenceServer (requests/s domain)."""
    import numpy as np

    from deeplearning4j_tpu.serving import (InferenceServer,
                                            InferenceSizeMix,
                                            ServingMetrics,
                                            build_schedule, run_load)
    net = _mlp()
    metrics = ServingMetrics(slo_target_ms=slo_ms)
    srv = InferenceServer(net, max_batch=8, max_wait_ms=2.0,
                          max_queue=1024, metrics=metrics,
                          tracer=tracer).start()
    mix = InferenceSizeMix(32)
    try:
        # compile every bucket program off the clock
        rng = np.random.default_rng(1)
        xs = rng.standard_normal((8, 32)).astype(np.float32)
        for burst in (1, 4, 8):
            for f in [srv.submit(x) for x in xs[:burst]]:
                f.result(120)
        curve = []
        for i, rate in enumerate(rates):
            sched = build_schedule(_process_for(process, rate), mix,
                                   n_req, seed=seed + i)
            pt = run_load(srv, sched)
            pt["offered_rate_target"] = rate
            pt["_offered"] = pt["schedule"]["offered_rps"]
            pt["_achieved"] = pt["requests_per_sec"]
            curve.append(pt)
        snap = metrics.snapshot()
    finally:
        srv.stop(timeout=120)
    return {"server": "microbatch", "process": process,
            "config": "MLP 32->64->10, max_batch=8 max_wait=2ms, "
                      f"{n_req} reqs/rate, slo={slo_ms:g}ms",
            "unit": "requests/sec",
            "curve": curve, "knee": _knee(curve)}, snap


def run_sweep(server="both", rates=(50, 100, 200, 400, 800),
              process="poisson", n_req=64, slo_ms=150.0, seed=0,
              trace=True, report_path=None, paged=False):
    """Drive the sweep(s) and (optionally) write the combined
    obs_report (JSON + text + Chrome trace). Returns the results list.
    The tier-1 smoke test calls this with tiny parameters (and once
    with paged=True so CI exercises the block-gated admission path)."""
    from deeplearning4j_tpu.obs import Tracer
    tracer = Tracer(capacity=1 << 16, enabled=True) if trace else None
    results, snaps = [], {}
    if server in ("decode", "both"):
        body, snap = sweep_decode(rates, n_req=n_req, slo_ms=slo_ms,
                                  seed=seed, process=process,
                                  tracer=tracer, paged=paged)
        results.append(body)
        snaps["decode"] = snap
    if server in ("microbatch", "both"):
        # the micro-batch rates ride the same ladder; its own tracer
        # would collide with the decode server's req-<id> lanes, so the
        # shared tracer is decode-only and decomposition covers decode
        mb_rates = tuple(max(20, r // 2) for r in rates)
        body, snap = sweep_microbatch(mb_rates, n_req=n_req,
                                      slo_ms=min(slo_ms, 50.0),
                                      seed=seed, process=process)
        results.append(body)
        snaps["microbatch"] = snap
    if report_path:
        # obs_report lives next to this file, not under the repo-root
        # entry this module inserts — `python -m tools.load_sweep` or an
        # importing test must not lose a finished sweep at report time
        tools_dir = os.path.dirname(os.path.abspath(__file__))
        if tools_dir not in sys.path:
            sys.path.insert(0, tools_dir)
        from obs_report import build_report, format_report
        report = build_report(spans=tracer, metrics=snaps)
        report["sweep"] = results
        with open(report_path + ".json", "w") as fh:
            json.dump(report, fh)
        with open(report_path + ".txt", "w") as fh:
            fh.write(format_report(report) + "\n")
            for r in results:
                fh.write(f"\n== sweep: {r['server']} ({r['process']}) "
                         f"==\n")
                for pt in r["curve"]:
                    fh.write(json.dumps(pt) + "\n")
                fh.write(json.dumps(r["knee"]) + "\n")
        if tracer is not None:
            tracer.save(report_path + ".trace.json")
    return results


def main():
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--server", default="both",
                    choices=("decode", "microbatch", "both"))
    ap.add_argument("--rates", default="50,100,200,400,800",
                    help="comma-separated offered rates (requests/sec; "
                         "concurrency levels for --process closed)")
    ap.add_argument("--process", default="poisson",
                    choices=("poisson", "onoff", "closed"))
    ap.add_argument("--requests", type=int, default=64,
                    help="requests per sweep point")
    ap.add_argument("--slo-ms", type=float, default=150.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--report", default=None,
                    help="write obs_report JSON/text/trace under this "
                         "path prefix")
    ap.add_argument("--no-trace", action="store_true",
                    help="disable span tracing (no decomposition in "
                         "the report)")
    ap.add_argument("--paged", action="store_true",
                    help="decode server uses the paged block-table KV "
                         "cache (equal-bytes arena) instead of fixed "
                         "slots")
    args = ap.parse_args()
    rates = tuple(float(r) for r in args.rates.split(","))
    t0 = time.perf_counter()
    results = run_sweep(server=args.server, rates=rates,
                        process=args.process, n_req=args.requests,
                        slo_ms=args.slo_ms, seed=args.seed,
                        trace=not args.no_trace,
                        report_path=args.report, paged=args.paged)
    for r in results:
        print(json.dumps(r))
    print(json.dumps({"elapsed_s": fmt(time.perf_counter() - t0, 1),
                      "report": args.report and args.report
                      + ".{json,txt,trace.json}"}))


if __name__ == "__main__":
    main()
