"""Serving-layer A/B on the CPU backend (no chip needed).

Two questions the serving subsystem (`deeplearning4j_tpu/serving/`)
exists to answer, measured through the REAL servers with the interleaved
same-process protocol (bench.py `_interleaved_median`: alternating short
segments, median per arm — tunnel weather / host jitter hits both arms
equally):

  * decode_continuous_vs_static — the SAME fixed-slot decode machinery
    with iteration-level scheduling (requests join/leave at token
    granularity, Orca) vs gang admission (a new batch only forms when
    every slot is free — classic static request batching). Mixed decode
    lengths are the point: under static batching a 4-token reply's slot
    idles while a 28-token reply finishes; continuous refills it.
  * speculative_vs_plain — the SAME continuous-batching scheduler with a
    K=4 n-gram prompt-lookup draft verified in one K-wide dispatch
    (serving/speculate.py) vs plain one-token-per-dispatch decode, on
    repetitive text. Token streams are pinned bit-identical;
    the A/B isolates dispatch amortization (dispatches/token, acceptance
    rate reported next to tokens/s).
  * paged_vs_fixed — the SAME continuous-decode scheduler over the paged
    block-table KV cache (serving/kvpool.py, `paged=True`) vs the
    fixed-slot cache, at EQUAL ARENA BYTES: fixed reserves
    slots x max_len rows up front, paged holds the same rows as
    free-listed blocks with slot count a pure scheduling width. The
    workload is mixed-length requests behind one shared system prefix
    (the dominant real-traffic shape), so the paged arm also exercises
    prefix reuse. Token streams are pinned bit-identical
    (tests/test_paged.py); the A/B isolates CONCURRENCY: max live
    streams (live_streams_max) and tokens/s at the same memory.
  * paged_spec_vs_paged — the SAME paged server config with and without
    a K=4 n-gram draft verified through the BLOCK-TABLE verify program
    (ISSUE 10: `make_paged_verify_fn` — speculation over the paged KV
    cache, the two biggest serving wins composed). Streams pinned
    bit-identical; the A/B isolates dispatch amortization on the paged
    layout (dispatches/token vs the paged baseline, acceptance, and the
    equal-arena concurrency class that must survive speculation).
  * fused_serve_vs_plain — the SAME paged continuous-decode scheduler
    with and without fused decode windows (ISSUE 18: `fused_serve=4` —
    `lax.scan` runs K=4 serve iterations on-device in ONE dispatch,
    static slot membership inside the window, admissions/evictions at
    window boundaries). Streams are pinned bit-identical
    (tests/test_fused_serve.py); the A/B isolates pure dispatch
    amortization on the dispatch-bound config: decode lengths are
    chosen ≡ 1 (mod K) so every window retires exactly K iterations
    and dispatches/token lands at 1/K of the unfused paged baseline,
    with tokens/s at parity or better even on compute-bound CPU.
  * preempt_vs_shed — durable-KV preemption (ISSUE 11: serving/
    kvstate.py) vs shed-only overload handling at FULL BLOCK OCCUPANCY:
    both arms run the same paged server with a brownout class ranking
    and the same workload — three long batch-class requests each
    reserving half the block pool (two resident cover it; the third
    sustains the pressure), then a stream of short deadline-carrying
    interactive requests. The shed-only arm's interactive
    requests park on the memory gate until the batch work completes or
    their deadlines expire; the preempt arm spills a batch slot to host
    (resumed later bit-identically) and admits them. The A/B isolates
    what preemption buys: INTERACTIVE-class goodput-under-deadline and
    completion p99 (a tight TTFT bound — interactive requests are 4
    tokens) at the occupancy regime queue-depth admission cannot help.
  * affinity_vs_least_backlog — the SAME seeded shared-system-prompt
    schedule (SharedPrefixMix) through two 2-replica paged fleets:
    FleetManager prefix-affinity routing (consistent-hash the block-
    aligned prefix key, load-aware spill, fleet prefix tier pulls) vs
    the least-backlog baseline (ISSUE 20). The A/B isolates what
    stickiness buys — fleet prefix hit rate (baseline decays toward
    ~1/N) at goodput parity or better; routing verdicts and pull
    counters reported alongside.
  * overload_vs_baseline — the SAME seeded past-knee arrival schedule
    (serving/loadgen.py, NOT a backlog: overload is a queueing
    phenomenon) through an uncontrolled decode server vs one with
    chunked prefill + deadline-aware admission (PR 9,
    serving/admission.py). The controlled arm sheds predicted deadline
    misses at enqueue instead of letting the queue eat the SLO, so the
    A/B isolates GOODPUT-under-SLO at saturation — raw tokens/s is the
    number overload control deliberately spends (shed breakdown
    reported per cause next to it).
  * microbatch_vs_per_request — InferenceServer's adaptive micro-batching
    (Clipper) vs the bare per-request `output()` loop the reference
    shipped. Dispatch-overhead-dominated small models are exactly the
    serving regime: N/8 batched dispatches beat N solo dispatches.
  * tracing_on_vs_off — the SAME continuous-decode scheduler with the
    obs tracer enabled vs disabled (the shipping default). Disabled is a
    few attribute checks per iteration (nanosecond-scale, pinned by
    tests/test_obs.py) — this arm bounds even the ENABLED cost, and pins
    that tracing adds zero device dispatches (dispatch counters must
    match across arms for the same workload).

Every arm reports deadline attainment and goodput-under-SLO
(`--slo-ms`, default 100 ms request SLO) next to raw tokens/s — the
pinned starting metric for the ROADMAP traffic-harness round. Metrics
read-outs are None-guarded through the shared `obs.registry.fmt` helper
(empty reservoirs report None, not a crash). `--report PATH` writes the
combined tools/obs_report.py view (host-span timeline + metrics
snapshots, plus the tracing arm's Chrome trace alongside).

Run:  JAX_PLATFORMS=cpu python tools/serve_ab.py [--segments N]
Numbers recorded in PERF.md ("serving layer"); on-chip re-measure armed
in ROADMAP (remote-attached dispatch makes batching wins larger).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# the ONE protocol implementation (see tools/fused_ab.py)
from bench import _interleaved_median as _interleaved  # noqa: E402
from deeplearning4j_tpu.obs.registry import fmt  # noqa: E402
# the ONE attainment/goodput implementation (shared with bench.py)
from deeplearning4j_tpu.serving.metrics import \
    slo_view as _slo_view  # noqa: E402
# the ONE shed-reason breakdown (PR 9; shared with loadgen/bench.py)
from deeplearning4j_tpu.serving.metrics import \
    shed_view as _shed_view  # noqa: E402


def _lm():
    import jax.numpy as jnp

    from deeplearning4j_tpu.models.zoo.transformer import TransformerLM
    return TransformerLM(96, d_model=32, n_heads=2, n_layers=2,
                         max_len=64, seed=5, dtype=jnp.float32)


def _mlp():
    from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    conf = (NeuralNetConfiguration.Builder().seed(7)
            .updater("adam").learning_rate(0.01).list()
            .layer(0, DenseLayer(n_out=64, activation="relu"))
            .layer(1, OutputLayer(n_out=10, activation="softmax",
                                  loss_function="mcxent"))
            .set_input_type(InputType.feed_forward(32))
            .build())
    return MultiLayerNetwork(conf).init()


def _decode_workload(rng, n):
    """Mixed sequence lengths — prompts spanning two buckets, decode
    lengths 4..43 (the spread static batching pays for)."""
    out = []
    for _ in range(n):
        p_len = int(rng.integers(3, 16))
        n_new = int(rng.integers(4, 44))
        out.append((rng.integers(1, 96, p_len).tolist(), n_new))
    return out


def bench_decode_ab(segments, reqs_per_seg=16, slo_ms=100.0):
    """continuous vs static decode batching: same model params, same slot
    program, same per-segment workload — only the SCHEDULER differs."""
    import numpy as np

    from deeplearning4j_tpu.serving import (ContinuousDecodeServer,
                                            ServingMetrics)

    lm = _lm()
    servers = {
        "continuous": ContinuousDecodeServer(
            lm, slots=4, prompt_buckets=(8, 16), max_queue=256,
            metrics=ServingMetrics(slo_target_ms=slo_ms)).start(),
        "static": ContinuousDecodeServer(
            lm, slots=4, prompt_buckets=(8, 16), max_queue=256,
            static_batching=True,
            metrics=ServingMetrics(slo_target_ms=slo_ms)).start(),
    }
    warm = _decode_workload(np.random.default_rng(0), 6)
    for srv in servers.values():        # compile off the clock
        for p, n in warm:
            srv.generate(p, n, timeout=120)
    # SLO baseline after warm-up: compile-latency misses stay off the books
    base = {n: servers[n].metrics.snapshot() for n in servers}

    seg_idx = {"continuous": [0], "static": [0]}

    def seg(name):
        srv = servers[name]

        def run():
            # identical per-segment workload for both arms, fresh per
            # segment index so neither arm replays a cached rng stream
            rng = np.random.default_rng(100 + seg_idx[name][0])
            seg_idx[name][0] += 1
            work = _decode_workload(rng, reqs_per_seg)
            toks = sum(n for _, n in work)
            t0 = time.perf_counter()
            futs = [srv.submit(p, n) for p, n in work]
            for f in futs:
                f.result(300)
            return toks / (time.perf_counter() - t0)
        return run

    ab = _interleaved({n: seg(n) for n in servers}, segments=segments)
    lat = {n: servers[n].metrics.snapshot() for n in servers}
    for srv in servers.values():
        srv.stop()
    return {
        "config": "TransformerLM L=2 d=32 slots=4, mixed prompts 3-15 / "
                  "decode 4-43 tokens, 16 reqs/segment, greedy",
        "unit": "generated tokens/sec",
        "ab": ab,
        "speedup_continuous_over_static": round(
            ab["continuous"]["median"] / ab["static"]["median"], 3),
        "request_latency_ms": {
            n: {"p50": fmt(lat[n]["latency_ms_p50"]),
                "p99": fmt(lat[n]["latency_ms_p99"])} for n in lat},
        "slot_occupancy_mean": {
            n: fmt(lat[n]["batch_occupancy_mean"]) for n in lat},
        "slo_ms": slo_ms,
        "slo": {n: _slo_view(lat[n], ab[n]["median"], base[n])
                for n in lat},
    }, lat, None


def bench_paged_ab(segments, reqs_per_seg=16, slo_ms=100.0):
    """paged vs fixed-slot decode cache at EQUAL ARENA BYTES: fixed =
    4 slots x 64 rows; paged = 32 blocks x 8 rows (the same 256 KV rows)
    with slots=16 as pure scheduling width. Requests share a 16-token
    system prefix (two full blocks — stored once in the paged arm) and
    spread over mixed prompt/decode lengths, so fixed mode is bounded by
    4 worst-case slots while paged admission is bounded by rows actually
    reserved. Streams are pinned bit-identical (tests/test_paged.py);
    here we measure what paging buys: max concurrent streams at the same
    memory, and the tokens/s that concurrency carries."""
    import numpy as np

    from deeplearning4j_tpu.serving import (ContinuousDecodeServer,
                                            ServingMetrics)

    lm = _lm()                          # max_len=64
    sys_prefix = np.random.default_rng(7).integers(1, 96, 16).tolist()

    def workload(rng, n):
        out = []
        for _ in range(n):
            own = rng.integers(1, 96, int(rng.integers(1, 8))).tolist()
            out.append((sys_prefix + own, int(rng.integers(4, 28))))
        return out

    servers = {
        "paged": ContinuousDecodeServer(
            lm, slots=16, prompt_buckets=(24,), max_queue=256,
            paged=True, block_size=8, n_blocks=32,
            metrics=ServingMetrics(slo_target_ms=slo_ms)).start(),
        "fixed": ContinuousDecodeServer(
            lm, slots=4, prompt_buckets=(24,), max_queue=256,
            metrics=ServingMetrics(slo_target_ms=slo_ms)).start(),
    }
    warm = workload(np.random.default_rng(0), 6)
    for srv in servers.values():        # compile off the clock
        for p, n in warm:
            srv.generate(p, n, timeout=120)
    # SLO baseline after warm-up: compile-latency misses stay off the books
    base = {n: servers[n].metrics.snapshot() for n in servers}

    seg_idx = {name: [0] for name in servers}

    def seg(name):
        srv = servers[name]

        def run():
            rng = np.random.default_rng(100 + seg_idx[name][0])
            seg_idx[name][0] += 1
            work = workload(rng, reqs_per_seg)
            toks = sum(n for _, n in work)
            t0 = time.perf_counter()
            futs = [srv.submit(p, n) for p, n in work]
            for f in futs:
                f.result(300)
            return toks / (time.perf_counter() - t0)
        return run

    ab = _interleaved({n: seg(n) for n in servers}, segments=segments)
    snaps = {n: servers[n].metrics.snapshot() for n in servers}
    for srv in servers.values():
        srv.stop()
    p = snaps["paged"]
    streams = {n: snaps[n]["live_streams_max"] for n in snaps}
    return {
        "config": "TransformerLM L=2 d=32, EQUAL ARENA (256 KV rows): "
                  "fixed 4 slots x 64 rows vs paged 32 blocks x 8 rows "
                  "(slots=16 scheduling width), 16-token shared system "
                  "prefix + mixed own prompts 1-7 / decode 4-27, "
                  "16 reqs/segment, greedy",
        "unit": "generated tokens/sec",
        "ab": ab,
        "speedup_paged_over_fixed": round(
            ab["paged"]["median"] / ab["fixed"]["median"], 3),
        "max_concurrent_streams": streams,
        "streams_paged_over_fixed": round(
            streams["paged"] / max(1, streams["fixed"]), 2),
        "arena_rows": {"paged": 32 * 8, "fixed": 4 * 64},
        "blocks_in_use_max": p["blocks_in_use_max"],
        "pool_blocks": p["pool_blocks"],
        "prefix_hit_rate": fmt(p["prefix_hit_rate"], 4),
        "cow_copies": p["cow_copies"],
        "blocked_on_memory": p["blocked_on_memory"],
        "dispatches_per_token": {
            n: fmt(snaps[n]["dispatches_per_token"], 4) for n in snaps},
        "request_latency_ms": {
            n: {"p50": fmt(snaps[n]["latency_ms_p50"]),
                "p99": fmt(snaps[n]["latency_ms_p99"])} for n in snaps},
        "slo_ms": slo_ms,
        "slo": {n: _slo_view(snaps[n], ab[n]["median"], base[n])
                for n in snaps},
    }, snaps, None


def bench_speculative_ab(segments, reqs_per_seg=16, slo_ms=100.0):
    """speculative vs plain greedy decode through the continuous-batching
    server: same model, same slot machinery, same per-segment workload —
    only the spec arm drafts (K=4 n-gram prompt-lookup) and verifies K
    tokens per dispatch. Streams are pinned bit-identical
    (tests/test_speculative.py), so the A/B isolates dispatch
    amortization: watch dispatches/token and acceptance next to tokens/s.
    Workload is repetitive text (short cyclic patterns the model is
    briefly trained to continue) — the prompt-lookup regime."""
    import numpy as np

    from deeplearning4j_tpu.models.zoo.transformer import TransformerLM
    from deeplearning4j_tpu.serving import (ContinuousDecodeServer,
                                            NGramDraft, ServingMetrics,
                                            Speculator)

    V, max_len = 96, 96
    lm = TransformerLM(V, d_model=32, n_heads=2, n_layers=2,
                       max_len=max_len, seed=5, learning_rate=0.3)
    T = 32
    r = np.random.default_rng(0)
    for _ in range(60):                 # off the clock: cycle continuation
        xs = []
        for _ in range(16):
            pat = r.integers(1, V, int(r.integers(2, 5))).tolist()
            xs.append((pat * (T // len(pat) + 2))[:T + 1])
        xs = np.asarray(xs, np.int32)
        lm.fit_batch(xs[:, :-1], xs[:, 1:])

    def workload(rng, n):
        out = []
        for _ in range(n):
            pat = rng.integers(1, V, int(rng.integers(2, 5))).tolist()
            p = (pat * 8)[:int(rng.integers(6, 16))]
            out.append((p, int(rng.integers(16, max_len - 16 - 4))))
        return out

    servers = {
        "speculative": ContinuousDecodeServer(
            lm, slots=4, prompt_buckets=(8, 16), max_queue=256,
            speculate=Speculator(NGramDraft(n=3), k=4),
            metrics=ServingMetrics(slo_target_ms=slo_ms)).start(),
        "plain": ContinuousDecodeServer(
            lm, slots=4, prompt_buckets=(8, 16), max_queue=256,
            metrics=ServingMetrics(slo_target_ms=slo_ms)).start(),
    }
    warm = workload(np.random.default_rng(0), 6)
    for srv in servers.values():        # compile off the clock
        for p, n in warm:
            srv.generate(p, n, timeout=120)
    # SLO baseline after warm-up: compile-latency misses stay off the books
    base = {n: servers[n].metrics.snapshot() for n in servers}

    seg_idx = {name: [0] for name in servers}

    def seg(name):
        srv = servers[name]

        def run():
            rng = np.random.default_rng(100 + seg_idx[name][0])
            seg_idx[name][0] += 1
            work = workload(rng, reqs_per_seg)
            toks = sum(n for _, n in work)
            t0 = time.perf_counter()
            futs = [srv.submit(p, n) for p, n in work]
            for f in futs:
                f.result(300)
            return toks / (time.perf_counter() - t0)
        return run

    ab = _interleaved({n: seg(n) for n in servers}, segments=segments)
    snaps = {n: servers[n].metrics.snapshot() for n in servers}
    for srv in servers.values():
        srv.stop()
    s = snaps["speculative"]
    return {
        "config": "TransformerLM L=2 d=32 slots=4 (trained on cyclic "
                  "patterns), n-gram draft K=4, repetitive prompts 6-15 / "
                  "decode 16-75 tokens, 16 reqs/segment, greedy",
        "unit": "generated tokens/sec",
        "ab": ab,
        "speedup_spec_over_plain": round(
            ab["speculative"]["median"] / ab["plain"]["median"], 3),
        "dispatches_per_token": {
            n: fmt(snaps[n]["dispatches_per_token"], 4) for n in snaps},
        "acceptance_rate": fmt(s["spec_acceptance_rate_mean"], 4),
        "accepted_per_dispatch": fmt(
            s["spec_accepted_per_dispatch_mean"], 3),
        "request_latency_ms": {
            n: {"p50": fmt(snaps[n]["latency_ms_p50"]),
                "p99": fmt(snaps[n]["latency_ms_p99"])} for n in snaps},
        "slo_ms": slo_ms,
        "slo": {n: _slo_view(snaps[n], ab[n]["median"], base[n])
                for n in snaps},
    }, snaps, None


def bench_paged_spec_ab(segments, reqs_per_seg=16, slo_ms=100.0):
    """paged+speculative vs paged plain decode (ISSUE 10): the SAME
    paged server config — block-table arena, 16-token shared system
    prefix stored once, slots a pure scheduling width — with and
    without a K=4 n-gram draft verified through the BLOCK-TABLE verify
    program (`make_paged_verify_fn`). Streams are pinned bit-identical
    (tests/test_paged.py), so the A/B isolates dispatch amortization ON
    the paged layout: the PR 5 win (dispatches/token 0.32 -> 0.14)
    re-measured over the PR 8 memory model, the two serving wins
    composed. Workload is repetitive text behind the shared prefix (the
    prompt-lookup regime on the real-traffic shape); watch
    dispatches/token spec vs plain (target <= 0.6x), tokens/s (>=
    parity on compute-bound CPU), and live_streams_max (the equal-arena
    concurrency class must survive speculation)."""
    import numpy as np

    from deeplearning4j_tpu.models.zoo.transformer import TransformerLM
    from deeplearning4j_tpu.serving import (ContinuousDecodeServer,
                                            NGramDraft, ServingMetrics,
                                            Speculator)

    V, max_len = 96, 96
    lm = TransformerLM(V, d_model=32, n_heads=2, n_layers=2,
                       max_len=max_len, seed=5, learning_rate=0.3)
    T = 32
    r = np.random.default_rng(0)
    for _ in range(60):                 # off the clock: cycle continuation
        xs = []
        for _ in range(16):
            pat = r.integers(1, V, int(r.integers(2, 5))).tolist()
            xs.append((pat * (T // len(pat) + 2))[:T + 1])
        xs = np.asarray(xs, np.int32)
        lm.fit_batch(xs[:, :-1], xs[:, 1:])
    sys_prefix = np.random.default_rng(7).integers(1, V, 16).tolist()

    def workload(rng, n):
        out = []
        for _ in range(n):
            pat = rng.integers(1, V, int(rng.integers(2, 5))).tolist()
            p = sys_prefix + (pat * 8)[:int(rng.integers(4, 15))]
            out.append((p, int(rng.integers(16, 41))))
        return out

    paged_kw = dict(slots=16, prompt_buckets=(32,), max_queue=256,
                    paged=True, block_size=8, n_blocks=48)
    servers = {
        "paged_spec": ContinuousDecodeServer(
            lm, speculate=Speculator(NGramDraft(n=3), k=4),
            metrics=ServingMetrics(slo_target_ms=slo_ms),
            **paged_kw).start(),
        "paged": ContinuousDecodeServer(
            lm, metrics=ServingMetrics(slo_target_ms=slo_ms),
            **paged_kw).start(),
    }
    warm = workload(np.random.default_rng(0), 6)
    for srv in servers.values():        # compile off the clock
        for p, n in warm:
            srv.generate(p, n, timeout=120)
    # SLO baseline after warm-up: compile-latency misses stay off the books
    base = {n: servers[n].metrics.snapshot() for n in servers}

    seg_idx = {name: [0] for name in servers}

    def seg(name):
        srv = servers[name]

        def run():
            rng = np.random.default_rng(100 + seg_idx[name][0])
            seg_idx[name][0] += 1
            work = workload(rng, reqs_per_seg)
            toks = sum(n for _, n in work)
            t0 = time.perf_counter()
            futs = [srv.submit(p, n) for p, n in work]
            for f in futs:
                f.result(300)
            return toks / (time.perf_counter() - t0)
        return run

    ab = _interleaved({n: seg(n) for n in servers}, segments=segments)
    snaps = {n: servers[n].metrics.snapshot() for n in servers}
    for srv in servers.values():
        srv.stop()
    s = snaps["paged_spec"]
    dpt = {n: snaps[n]["dispatches_per_token"] for n in snaps}
    return {
        "config": "TransformerLM L=2 d=32 (trained on cyclic patterns), "
                  "BOTH arms paged 48 blocks x 8 rows (slots=16 "
                  "scheduling width), 16-token shared system prefix + "
                  "repetitive own prompts 4-14 / decode 16-40, n-gram "
                  "draft K=4 on the spec arm, 16 reqs/segment, greedy",
        "unit": "generated tokens/sec",
        "ab": ab,
        "speedup_spec_over_paged": round(
            ab["paged_spec"]["median"] / ab["paged"]["median"], 3),
        "dispatches_per_token": {n: fmt(dpt[n], 4) for n in dpt},
        "dispatches_per_token_ratio": round(
            dpt["paged_spec"] / dpt["paged"], 3),
        "acceptance_rate": fmt(s["spec_acceptance_rate_mean"], 4),
        "accepted_per_dispatch": fmt(
            s["spec_accepted_per_dispatch_mean"], 3),
        "max_concurrent_streams": {
            n: snaps[n]["live_streams_max"] for n in snaps},
        "prefix_hit_rate": {
            n: fmt(snaps[n]["prefix_hit_rate"], 4) for n in snaps},
        "cow_copies": {n: snaps[n]["cow_copies"] for n in snaps},
        "blocked_on_memory": {
            n: snaps[n]["blocked_on_memory"] for n in snaps},
        "request_latency_ms": {
            n: {"p50": fmt(snaps[n]["latency_ms_p50"]),
                "p99": fmt(snaps[n]["latency_ms_p99"])} for n in snaps},
        "slo_ms": slo_ms,
        "slo": {n: _slo_view(snaps[n], ab[n]["median"], base[n])
                for n in snaps},
    }, snaps, None


def bench_fused_serve_ab(segments, reqs_per_seg=16, slo_ms=100.0):
    """fused windows vs plain iteration dispatch (ISSUE 18): the SAME
    paged server config — block-table arena, 16-token shared system
    prefix, slots a pure scheduling width — with and without
    `fused_serve=4` (K serve iterations scanned into one device
    dispatch, static slot membership inside the window). Streams are
    pinned bit-identical (tests/test_fused_serve.py), so the A/B
    isolates dispatch amortization with NO model-dependence (unlike
    speculation there is no acceptance rate: the win is purely
    dispatches/token). Decode lengths are all ≡ 1 (mod 4) so every
    request's post-prefill iteration count is a multiple of K and every
    window retires exactly K iterations — the measured
    dispatches/token ratio is the clean 1/K floor, not a
    ragged-tail approximation. Watch dispatches/token fused vs plain
    (target <= 1/K) and tokens/s (>= parity on compute-bound CPU; the
    on-chip backlog re-measures where each dispatch is a tunnel hop)."""
    import numpy as np

    from deeplearning4j_tpu.serving import (ContinuousDecodeServer,
                                            ServingMetrics)

    K = 4
    lm = _lm()                          # max_len=64
    sys_prefix = np.random.default_rng(7).integers(1, 96, 16).tolist()

    def workload(rng, n):
        # prompt 16+1..7 = 17..23 rows; decode lengths 17/21/25/29/33
        # (all ≡ 1 mod K: prefill emits token 1, the remaining
        # n_new - 1 iterations divide evenly into full K-windows)
        out = []
        for _ in range(n):
            own = rng.integers(1, 96, int(rng.integers(1, 8))).tolist()
            n_new = int(rng.choice((17, 21, 25, 29, 33)))
            out.append((sys_prefix + own, n_new))
        return out

    paged_kw = dict(slots=16, prompt_buckets=(24,), max_queue=256,
                    paged=True, block_size=8, n_blocks=48)
    servers = {
        "fused": ContinuousDecodeServer(
            lm, fused_serve=K,
            metrics=ServingMetrics(slo_target_ms=slo_ms),
            **paged_kw).start(),
        "plain": ContinuousDecodeServer(
            lm, metrics=ServingMetrics(slo_target_ms=slo_ms),
            **paged_kw).start(),
    }
    warm = workload(np.random.default_rng(0), 6)
    for srv in servers.values():        # compile off the clock
        for p, n in warm:
            srv.generate(p, n, timeout=120)
    # SLO baseline after warm-up: compile-latency misses stay off the books
    base = {n: servers[n].metrics.snapshot() for n in servers}

    seg_idx = {name: [0] for name in servers}

    def seg(name):
        srv = servers[name]

        def run():
            rng = np.random.default_rng(100 + seg_idx[name][0])
            seg_idx[name][0] += 1
            work = workload(rng, reqs_per_seg)
            toks = sum(n for _, n in work)
            t0 = time.perf_counter()
            futs = [srv.submit(p, n) for p, n in work]
            for f in futs:
                f.result(300)
            return toks / (time.perf_counter() - t0)
        return run

    ab = _interleaved({n: seg(n) for n in servers}, segments=segments)
    snaps = {n: servers[n].metrics.snapshot() for n in servers}
    for srv in servers.values():
        srv.stop()
    dpt = {n: snaps[n]["dispatches_per_token"] for n in snaps}
    return {
        "config": f"TransformerLM L=2 d=32, BOTH arms paged 48 blocks "
                  f"x 8 rows (slots=16 scheduling width), 16-token "
                  f"shared system prefix + mixed own prompts 1-7 / "
                  f"decode 17-33 (≡1 mod {K}), fused_serve={K} on the "
                  f"fused arm, {reqs_per_seg} reqs/segment, greedy",
        "unit": "generated tokens/sec",
        "ab": ab,
        "speedup_fused_over_plain": round(
            ab["fused"]["median"] / ab["plain"]["median"], 3),
        "fused_k": K,
        "dispatches_per_token": {n: fmt(dpt[n], 4) for n in dpt},
        # the acceptance pin: fused dpt at or below 1/K of unfused
        "dispatches_per_token_ratio": round(dpt["fused"] / dpt["plain"],
                                            4) if dpt["plain"] else None,
        "target_ratio": round(1.0 / K, 4),
        "fused_windows": snaps["fused"]["fused_windows"],
        "iterations_per_dispatch": {
            n: fmt(snaps[n]["iterations_per_dispatch"], 3)
            for n in snaps},
        "max_concurrent_streams": {
            n: snaps[n]["live_streams_max"] for n in snaps},
        "blocked_on_memory": {
            n: snaps[n]["blocked_on_memory"] for n in snaps},
        "request_latency_ms": {
            n: {"p50": fmt(snaps[n]["latency_ms_p50"]),
                "p99": fmt(snaps[n]["latency_ms_p99"])} for n in snaps},
        "slo_ms": slo_ms,
        "slo": {n: _slo_view(snaps[n], ab[n]["median"], base[n])
                for n in snaps},
    }, snaps, None


def bench_preempt_ab(segments, reqs_per_seg=12, slo_ms=60.0):
    """Preemption vs shed-only at full block occupancy (module
    docstring). Per segment: 3 batch-class requests of 14 blocks each
    against a 28-block pool (two resident reserve it WHOLE, the third
    keeps it full when one completes), then `reqs_per_seg` interactive
    requests (4 tokens each, deadline = slo); the metric is interactive-class
    goodput-under-deadline, computed CLIENT-side per class (deadline
    known at submit, completion observed, tokens known) because the
    server's SLO counters aggregate classes. Both arms also report the
    interactive completion p99 — a tight TTFT bound at 4 tokens — and
    the preempt arm's spill accounting. The shed-only arm's interactive
    requests can only park on the memory gate until batch work
    completes or their deadline sweeps them; the preempt arm spills a
    batch slot and serves them inside the deadline."""
    import numpy as np

    from deeplearning4j_tpu.models.zoo.transformer import TransformerLM
    from deeplearning4j_tpu.serving import (BrownoutPolicy,
                                            ContinuousDecodeServer,
                                            ServingMetrics)

    # a somewhat bigger model than the other arms': batch occupancy
    # must OUTLAST the interactive deadline for full occupancy to be a
    # regime rather than a blip (the tiny shared model finishes 44
    # tokens inside the deadline and both arms trivially tie)
    lm = TransformerLM(96, d_model=64, n_heads=4, n_layers=3,
                       max_len=128, seed=5)

    def mk(preempt):
        return ContinuousDecodeServer(
            lm, slots=4, prompt_buckets=(8, 16), max_queue=256,
            paged=True, block_size=8, n_blocks=28,
            brownout=BrownoutPolicy(classes={"batch": (0.9, 1.01)}),
            preempt=preempt,
            metrics=ServingMetrics(slo_target_ms=slo_ms)).start()

    servers = {"preempt": mk(True), "shed_only": mk(False)}
    for name, srv in servers.items():   # compile off the clock —
        # including the preempt arm's extract/restore programs: one
        # full-pool batch pair + one preempting interactive request
        srv.generate([1, 2, 3, 4], 4, timeout=300)
        srv.generate(list(range(1, 11)), 4, timeout=300)
        warm_b = [srv.submit(list(range(1, 10)), 100, klass="batch")
                  for _ in range(2)]
        time.sleep(0.02)
        try:
            srv.generate([5, 6, 7], 4, deadline_ms=10_000, timeout=300)
        except Exception:               # noqa: BLE001 — shed arm: parks
            pass
        for f in warm_b:
            f.result(600)
    base = {n: servers[n].metrics.snapshot() for n in servers}
    seg_idx = {n: [0] for n in servers}
    inter_lat = {n: [] for n in servers}    # interactive completion ms

    def seg(name):
        srv = servers[name]

        def run():
            rng = np.random.default_rng(300 + seg_idx[name][0])
            seg_idx[name][0] += 1
            t0 = time.perf_counter()
            # three batch requests, each reserving HALF the pool
            # (prompt 9 + 100 new = 108 reserved rows = 14 blocks): two
            # run, the third keeps the pool full when one completes —
            # occupancy pressure lasts the whole interactive stream (no
            # deadline: batch is throughput work)
            batch = [srv.submit(
                rng.integers(1, 96, 9).tolist(), 100, klass="batch")
                for _ in range(3)]
            time.sleep(0.02)            # let them admit + occupy
            inter = []
            for _ in range(reqs_per_seg):
                p = rng.integers(1, 96, int(rng.integers(3, 8))).tolist()
                dl = time.perf_counter()
                try:
                    f = srv.submit(p, 4, deadline_ms=slo_ms,
                                   klass="interactive")
                except Exception:       # noqa: BLE001 — shed: a miss
                    inter.append((None, dl, 4))
                    continue
                inter.append((f, dl, 4))
                time.sleep(0.004)
            good_tokens = 0
            for f, t_sub, toks in inter:
                if f is None:
                    continue
                try:
                    f.result(300)
                except Exception:       # noqa: BLE001 — shed/evicted
                    continue
                done = time.perf_counter()
                inter_lat[name].append((done - t_sub) * 1e3)
                if (done - t_sub) * 1e3 <= slo_ms:
                    good_tokens += toks
            for f in batch:             # drain: pool clean per segment
                f.result(600)
            return good_tokens / (time.perf_counter() - t0)
        return run

    ab = _interleaved({n: seg(n) for n in servers}, segments=segments)
    snaps = {n: servers[n].metrics.snapshot() for n in servers}
    for srv in servers.values():
        srv.stop(timeout=120)

    def pct(xs, q):
        xs = sorted(xs)
        return fmt(xs[min(len(xs) - 1, int(q / 100 * len(xs)))]) \
            if xs else None

    d = {n: snaps[n]["dispatches"] - base[n]["dispatches"]
         for n in snaps}
    return {
        "config": f"TransformerLM L=3 d=64 paged 28 blocks x 8 rows, "
                  f"3 batch reqs (14 blocks each, 100 tokens) + "
                  f"{reqs_per_seg} interactive 4-token reqs/segment at "
                  f"deadline {slo_ms:g}ms; brownout ranks batch < "
                  f"interactive, preempt arm spills batch to host",
        "unit": "interactive goodput tokens/sec (within deadline)",
        "ab": ab,
        "interactive_goodput_preempt_over_shed": round(
            ab["preempt"]["median"] / ab["shed_only"]["median"], 3)
        if ab["shed_only"]["median"] else None,
        "interactive_completion_ms": {
            n: {"p50": pct(inter_lat[n], 50),
                "p99": pct(inter_lat[n], 99)} for n in inter_lat},
        "preempted": {n: snaps[n]["preempted"] for n in snaps},
        "resumed": {n: snaps[n]["resumed"] for n in snaps},
        "spill_bytes": {n: snaps[n]["spill_bytes"] for n in snaps},
        "blocked_on_memory": {
            n: snaps[n]["blocked_on_memory"] - base[n][
                "blocked_on_memory"] for n in snaps},
        "sheds": {n: _shed_view(snaps[n], base[n]) for n in snaps},
        "measured_dispatches": d,
        "slo_ms": slo_ms,
        "slo": {n: _slo_view(snaps[n], None, base[n]) for n in snaps},
    }, snaps, None


def bench_affinity_ab(segments, reqs_per_seg=24, slo_ms=250.0):
    """Prefix-affinity routing A/B (ISSUE 20): the SAME seeded
    shared-system-prompt schedule (`serving.loadgen.SharedPrefixMix`)
    replayed per segment through two 2-replica paged fleets —
    `FleetManager(policy="affinity")` (consistent-hash prefix routing
    with load-aware spill + the fleet prefix tier) vs
    `policy="least_backlog"` (the prefix-blind baseline). Per-segment
    metric: fleet goodput-under-SLO. The record carries each arm's
    fleet prefix HIT RATE over the measured segments (counter deltas —
    warmup and the per-arm steady-state preload excluded) and the
    affinity arm's routing/pull counters: stickiness must BUY reuse
    (hit rate above the baseline's) without costing goodput."""
    from deeplearning4j_tpu.serving import (ContinuousDecodeServer,
                                            FleetManager,
                                            PoissonProcess,
                                            ServingMetrics,
                                            SharedPrefixMix,
                                            build_schedule, run_load)

    lm = _lm()
    mix = SharedPrefixMix(n_prefixes=4, prefix_blocks=(1, 3),
                          block_size=8, suffix=(1, 9), new=(4, 16),
                          vocab=96, seed=11)
    rate = 40.0     # near the 2-replica knee: enough concurrency that
    # routing placement matters, while goodput-under-SLO stays nonzero
    # (far past it every arm's goodput is 0 and the A/B reads nothing)

    def factory(name):
        return ContinuousDecodeServer(
            lm, slots=2, prompt_buckets=(16, 32), max_queue=1024,
            metrics=ServingMetrics(slo_target_ms=slo_ms, name=name),
            instance=name, admission=True, default_deadline_ms=slo_ms,
            paged=True, block_size=8)

    def warmup(srv):
        for p in ([1, 2, 3, 4], list(range(1, 25))):
            srv.generate(p, 4, deadline_ms=600_000, timeout=300)

    mgrs = {
        "affinity": FleetManager(
            factory, n_replicas=2, policy="affinity", warmup=warmup,
            metrics=ServingMetrics(name="fleet")),
        "least_backlog": FleetManager(
            factory, n_replicas=2, policy="least_backlog",
            warmup=warmup, metrics=ServingMetrics(name="fleet")),
    }
    for m in mgrs.values():
        m.start()
        # steady-state preload through the arm's OWN router: cold
        # first-touch misses are placement noise, not policy signal
        for p in mix.prefixes:
            m.generate(list(p) + [1, 2], 4, deadline_ms=600_000,
                       timeout=300)

    def tier(m):
        out = {"hit": 0, "total": 0}
        for n in list(m.replicas):
            s = m.replica(n).metrics.snapshot()
            out["hit"] += int(s.get("prefix_rows_hit") or 0)
            out["total"] += int(s.get("prefix_rows_total") or 0)
        return out

    base = {n: tier(m) for n, m in mgrs.items()}
    base_fleet = {n: m.fleet_snapshot() for n, m in mgrs.items()}
    seg_idx = {n: [0] for n in mgrs}
    last = {n: None for n in mgrs}

    def seg(name):
        m = mgrs[name]

        def run():
            sched = build_schedule(PoissonProcess(rate), mix,
                                   reqs_per_seg,
                                   seed=70 + seg_idx[name][0])
            seg_idx[name][0] += 1
            # fleet goodput = FEDERATED within-SLO tokens over the
            # segment (run_load's own slo view reads the MANAGER's
            # metrics, which never see the replicas' slo counters)
            g0 = m.fleet_view().counter("slo_tokens_met")
            pt = run_load(m, sched)
            last[name] = pt
            g1 = m.fleet_view().counter("slo_tokens_met")
            return (g1 - g0) / max(float(pt["duration_s"]), 1e-9)
        return run

    ab = _interleaved({n: seg(n) for n in mgrs}, segments=segments)
    tiers = {n: tier(m) for n, m in mgrs.items()}
    fleets = {n: m.fleet_snapshot() for n, m in mgrs.items()}
    snaps = {}
    for n, m in mgrs.items():
        for rn in list(m.replicas):
            snaps[f"{n}.{rn}"] = m.replica(rn).metrics.snapshot()
    for m in mgrs.values():
        m.stop(timeout=120)
    hr = {}
    for n in mgrs:
        h = tiers[n]["hit"] - base[n]["hit"]
        t = tiers[n]["total"] - base[n]["total"]
        hr[n] = (h / t) if t else None
    ga, gb = ab["affinity"]["median"], ab["least_backlog"]["median"]
    af, bf = fleets["affinity"], base_fleet["affinity"]
    return {
        "config": f"2x FleetManager over 2 paged (bs=8) replicas "
                  f"each, SharedPrefixMix P=4, Poisson {rate:g} rps, "
                  f"{reqs_per_seg} reqs/segment, slo={slo_ms:g}ms; "
                  f"affinity = consistent-hash prefix routing + "
                  f"fleet prefix tier vs least-backlog",
        "unit": "goodput tokens/sec (within-SLO, fleet)",
        "ab": ab,
        "goodput_affinity_over_least_backlog": round(ga / gb, 3)
        if gb else None,
        "fleet_prefix_hit_rate": {n: fmt(hr[n], 4) for n in hr},
        "routing": {
            "routed_affinity": af["fleet_routed_affinity"]
            - bf["fleet_routed_affinity"],
            "routed_spill": af["fleet_routed_spill"]
            - bf["fleet_routed_spill"],
            "prefix_pull_hits": af["fleet_prefix_pull_hits"]
            - bf["fleet_prefix_pull_hits"],
            "prefix_pull_bytes": af["fleet_prefix_pull_bytes"]
            - bf["fleet_prefix_pull_bytes"]},
        "tokens_per_sec_last_segment": {
            n: last[n] and last[n]["tokens_per_sec"] for n in last},
        "slo_ms": slo_ms,
    }, snaps, None


def bench_overload_ab(segments, reqs_per_seg=320, slo_ms=120.0):
    """Overload robustness A/B (PR 9): the SAME seeded Poisson schedule,
    offered well past the tiny model's saturation knee, replayed per
    segment through an uncontrolled baseline decode server and one with
    chunked prefill + deadline-aware admission. The per-segment metric
    is GOODPUT-under-SLO (tokens/s landing within deadline) — the
    number the PR 7 curve showed collapsing past the knee; raw
    throughput is reported alongside (the controlled arm deliberately
    spends it on sheds). Interleaved same-process protocol like every
    other arm."""
    from deeplearning4j_tpu.serving import (ContinuousDecodeServer,
                                            DecodeSizeMix,
                                            PoissonProcess,
                                            ServingMetrics,
                                            build_schedule, run_load)

    lm = _lm()
    mix = DecodeSizeMix(((0.8, (3, 12), (4, 24)),
                         (0.2, (8, 16), (24, 44))), vocab=96)
    rate = 1600.0   # far past the tiny model's knee: the arrival
    # window offers several seconds of work in ~0.2 s, so every segment
    # spends most of its life in the saturated regime the arm measures
    servers = {
        "baseline": ContinuousDecodeServer(
            lm, slots=4, prompt_buckets=(8, 16), max_queue=1024,
            metrics=ServingMetrics(slo_target_ms=slo_ms)).start(),
        "controlled": ContinuousDecodeServer(
            lm, slots=4, prompt_buckets=(8, 16), max_queue=1024,
            chunked_prefill=8, admission=True,
            default_deadline_ms=slo_ms,
            metrics=ServingMetrics(slo_target_ms=slo_ms)).start(),
    }
    for srv in servers.values():        # compile off the clock
        # explicit generous deadline: the controlled arm's DEFAULT
        # deadline is the SLO, which first-compile latency would blow
        for p in ([1, 2, 3, 4], list(range(1, 13))):
            srv.generate(p, 4, deadline_ms=600_000, timeout=300)
    base = {n: servers[n].metrics.snapshot() for n in servers}

    seg_idx = {n: [0] for n in servers}
    last = {n: None for n in servers}

    def seg(name):
        srv = servers[name]

        def run():
            sched = build_schedule(PoissonProcess(rate), mix,
                                   reqs_per_seg,
                                   seed=40 + seg_idx[name][0])
            seg_idx[name][0] += 1
            pt = run_load(srv, sched)
            last[name] = pt
            return (pt["slo"].get("goodput_tokens_per_sec") or 0.0)
        return run

    ab = _interleaved({n: seg(n) for n in servers}, segments=segments)
    snaps = {n: servers[n].metrics.snapshot() for n in servers}
    for srv in servers.values():
        srv.stop(timeout=120)
    gb, gc = ab["baseline"]["median"], ab["controlled"]["median"]
    return {
        "config": f"TransformerLM L=2 d=32 slots=4, Poisson {rate:g} "
                  f"rps (far past knee), {reqs_per_seg} reqs/segment, "
                  f"slo={slo_ms:g}ms; controlled = chunk=8 + "
                  f"deadline-aware admission",
        "unit": "goodput tokens/sec (within-SLO)",
        "ab": ab,
        "goodput_controlled_over_baseline": round(gc / gb, 3) if gb
        else None,
        "tokens_per_sec_last_segment": {
            n: last[n] and last[n]["tokens_per_sec"] for n in last},
        "ttft_ms_p99_last_segment": {
            n: last[n] and last[n].get("ttft_ms_p99") for n in last},
        "sheds": {n: _shed_view(snaps[n], base[n]) for n in snaps},
        "admission_error_ms": {
            "p50": fmt(snaps["controlled"]["admission_error_ms_p50"]),
            "p99": fmt(snaps["controlled"]["admission_error_ms_p99"]),
            "count": snaps["controlled"]["admission_error_ms_count"]},
        "service_rate_tokens_per_sec": fmt(
            snaps["controlled"]["service_rate_tokens_per_sec"], 1),
        "slo_ms": slo_ms,
        "slo": {n: _slo_view(snaps[n], None, base[n]) for n in snaps},
    }, snaps, None


def bench_microbatch_ab(segments, reqs_per_seg=96, slo_ms=100.0):
    """InferenceServer micro-batching vs a bare per-request output()
    loop over the same request stream."""
    import numpy as np

    from deeplearning4j_tpu.serving import InferenceServer, ServingMetrics

    net = _mlp()
    rng = np.random.default_rng(1)
    xs = rng.standard_normal((reqs_per_seg, 32)).astype(np.float32)
    srv = InferenceServer(net, max_batch=8, max_wait_ms=2.0,
                          max_queue=2 * reqs_per_seg,
                          metrics=ServingMetrics(
                              slo_target_ms=slo_ms)).start()
    # compile EVERY bucket program + the per-request jit off the clock
    for burst in (1, 4, 8):
        for f in [srv.submit(x) for x in xs[:burst]]:
            f.result(60)
    net.output(xs[:1])
    # SLO baseline after warm-up: compile-latency misses stay off the books
    base = srv.metrics.snapshot()

    def seg_server():
        t0 = time.perf_counter()
        futs = [srv.submit(x) for x in xs]
        for f in futs:
            f.result(120)
        return reqs_per_seg / (time.perf_counter() - t0)

    def seg_per_request():
        t0 = time.perf_counter()
        for x in xs:
            np.asarray(net.output(x[None]))
        return reqs_per_seg / (time.perf_counter() - t0)

    ab = _interleaved({"microbatch": seg_server,
                       "per_request": seg_per_request},
                      segments=segments)
    snap = srv.metrics.snapshot()
    srv.stop()
    return {
        "config": "MLP 32->64->10, 96 requests/segment, max_batch=8 "
                  "max_wait=2ms buckets(2,4,8)",
        "unit": "requests/sec",
        "ab": ab,
        "speedup_microbatch_over_per_request": round(
            ab["microbatch"]["median"] / ab["per_request"]["median"], 3),
        "request_latency_ms": {"p50": fmt(snap["latency_ms_p50"]),
                               "p99": fmt(snap["latency_ms_p99"])},
        "batch_size_mean": fmt(snap["batch_size_mean"], 2),
        "slo_ms": slo_ms,
        "slo": {"microbatch": _slo_view(snap, ab["microbatch"]["median"],
                                        base)},
    }, {"microbatch": snap}, None


def bench_tracing_ab(segments, reqs_per_seg=16, slo_ms=100.0):
    """Tracing-enabled vs tracing-disabled through the SAME continuous
    decode scheduler: the disabled arm is the shipping default (a few
    attribute checks per call site — the claim "tracing off adds ~zero
    over the pre-obs serve path" rests on the nanosecond-scale disabled
    span pin in tests/test_obs.py); this A/B bounds the ENABLED cost and
    pins that spans add ZERO device dispatches (the two arms' dispatch
    counters must agree for the same workload). Returns the enabled
    arm's tracer so main() can write a real Chrome trace."""
    import numpy as np

    from deeplearning4j_tpu.obs import Tracer
    from deeplearning4j_tpu.serving import (ContinuousDecodeServer,
                                            ServingMetrics)

    lm = _lm()
    tracer_on = Tracer(capacity=1 << 16, enabled=True)
    tracer_off = Tracer(enabled=False)
    servers = {
        "tracing_off": ContinuousDecodeServer(
            lm, slots=4, prompt_buckets=(8, 16), max_queue=256,
            tracer=tracer_off,
            metrics=ServingMetrics(slo_target_ms=slo_ms)).start(),
        "tracing_on": ContinuousDecodeServer(
            lm, slots=4, prompt_buckets=(8, 16), max_queue=256,
            tracer=tracer_on,
            metrics=ServingMetrics(slo_target_ms=slo_ms)).start(),
    }
    warm = _decode_workload(np.random.default_rng(0), 6)
    for srv in servers.values():        # compile off the clock
        for p, n in warm:
            srv.generate(p, n, timeout=120)
    # baseline after warm-up: both the dispatch-equality pin and the SLO
    # read-outs cover only the measured workload
    base = {n: s.metrics.snapshot() for n, s in servers.items()}

    seg_idx = {n: [0] for n in servers}

    def seg(name):
        srv = servers[name]

        def run():
            rng = np.random.default_rng(100 + seg_idx[name][0])
            seg_idx[name][0] += 1
            work = _decode_workload(rng, reqs_per_seg)
            toks = sum(n for _, n in work)
            t0 = time.perf_counter()
            futs = [srv.submit(p, n) for p, n in work]
            for f in futs:
                f.result(300)
            return toks / (time.perf_counter() - t0)
        return run

    ab = _interleaved({n: seg(n) for n in servers}, segments=segments)
    snaps = {n: servers[n].metrics.snapshot() for n in servers}
    disp = {n: snaps[n]["dispatches"] - base[n]["dispatches"]
            for n in snaps}
    for srv in servers.values():
        srv.stop()
    return {
        "config": "TransformerLM L=2 d=32 slots=4, same mixed workload "
                  "as decode A/B; obs tracer on vs off (off = shipping "
                  "default)",
        "unit": "generated tokens/sec",
        "ab": ab,
        "tracing_on_over_off": round(
            ab["tracing_on"]["median"] / ab["tracing_off"]["median"], 3),
        # span recording must never change WHAT runs on the device:
        # identical workload -> identical dispatch count
        "measured_dispatches": disp,
        "zero_extra_dispatches": disp["tracing_on"] == disp[
            "tracing_off"],
        "spans_recorded": len(tracer_on),
        "slo_ms": slo_ms,
        "slo": {n: _slo_view(snaps[n], ab[n]["median"], base[n])
                for n in snaps},
    }, snaps, tracer_on


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--segments", type=int, default=5)
    ap.add_argument("--slo-ms", type=float, default=100.0,
                    help="request SLO for attainment/goodput read-outs")
    ap.add_argument("--report", default=None,
                    help="write the combined obs report (text + JSON + "
                         "Chrome trace) under this path prefix")
    args = ap.parse_args()
    all_snaps = {}
    tracer = None
    benches = (("decode_continuous_vs_static", bench_decode_ab),
               ("paged_vs_fixed", bench_paged_ab),
               ("preempt_vs_shed", bench_preempt_ab),
               ("overload_vs_baseline", bench_overload_ab),
               ("speculative_vs_plain", bench_speculative_ab),
               ("paged_spec_vs_paged", bench_paged_spec_ab),
               ("fused_serve_vs_plain", bench_fused_serve_ab),
               ("affinity_vs_least_backlog", bench_affinity_ab),
               ("microbatch_vs_per_request", bench_microbatch_ab),
               ("tracing_on_vs_off", bench_tracing_ab))
    for name, fn in benches:
        rec = {"name": name}
        # uniform contract: every bench returns (body, snaps, tracer-or-
        # None); only the tracing A/B carries a tracer for the report
        body, snaps, tracer_arm = fn(args.segments, slo_ms=args.slo_ms)
        if tracer_arm is not None:
            tracer = tracer_arm
        rec.update(body)
        for arm, snap in snaps.items():
            all_snaps[f"{name}.{arm}"] = snap
        print(json.dumps(rec))
    if args.report:
        # the combined tools/obs_report.py view replaces the old
        # print-only summaries: host spans (from the tracing arm) +
        # every arm's metrics snapshot, one text + one JSON + the raw
        # Chrome trace for Perfetto
        from obs_report import build_report, format_report
        report = build_report(spans=tracer, metrics=all_snaps)
        with open(args.report + ".json", "w") as fh:
            json.dump(report, fh)
        with open(args.report + ".txt", "w") as fh:
            fh.write(format_report(report) + "\n")
        if tracer is not None:
            tracer.save(args.report + ".trace.json")
        print(json.dumps({"report": args.report + ".{json,txt}",
                          "trace": args.report + ".trace.json"}))


if __name__ == "__main__":
    main()
