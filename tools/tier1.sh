#!/usr/bin/env bash
# Tier-1 verify — the ONE copy of the ROADMAP.md "Tier-1 verify" command
# (kept verbatim below), so the builder, docs, and CI invoke one script
# instead of three hand-copied variants. Exit code is pytest's; the
# DOTS_PASSED line reports the progress-dot count parsed from the log.
# Extra arguments pass through to pytest (CI adds --durations=25 and
# --junitxml so per-test timing regressions are visible per-PR); with no
# arguments the behavior is byte-identical to the ROADMAP command.
cd "$(dirname "$0")/.." || exit 1
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly "$@" 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c); exit $rc
