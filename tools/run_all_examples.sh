#!/usr/bin/env bash
# Run EVERY example end-to-end on the CPU backend (virtual 8-device mesh)
# and report pass/fail per file — the full-bitrot sweep behind the
# examples test tier (tests/test_examples.py runs a fast subset; this is
# the whole set, ~15-25 min on a single-core box).
#
# Usage: tools/run_all_examples.sh [timeout_seconds_per_example]
set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
T="${1:-360}"
fails=0
cd "$REPO/examples"
for f in *.py; do
  [ "$f" = "_common.py" ] && continue
  if timeout "$T" env PYTHONPATH="$REPO" JAX_PLATFORMS=cpu \
      XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python "$f" >"/tmp/example_$f.out" 2>&1 < /dev/null; then
    echo "PASS $f"
  else
    echo "FAIL $f (rc=$?, log /tmp/example_$f.out)"
    fails=$((fails + 1))
  fi
done
exit "$fails"
