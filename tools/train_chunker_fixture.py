"""Train the committed chunker fixture (tests/fixtures/chunk_model.json.gz).

Corpus: BIO chunk tags over the hand-tagged POS corpus
(tools/train_pos_fixture.py), derived by DISTILLING the rule chunker
(`treeparser._chunk`) on the gold POS tags — the trained model learns the
same phrase grammar from features (word/POS context + tag history) and
generalizes it to unseen words and heuristic-POS noise, the role OpenNLP's
en-chunker.bin plays for the reference. Rerun after changing the chunker,
features or corpus:

    python tools/train_chunker_fixture.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deeplearning4j_tpu.text.pos_model import PerceptronChunker  # noqa: E402
from deeplearning4j_tpu.text.treeparser import _chunk  # noqa: E402
from train_pos_fixture import HELDOUT, TRAIN  # noqa: E402


def to_bio(sent):
    """[(word, pos)] -> [((word, pos), bio-tag)] via the rule chunker."""
    toks = [(w, p, i, i + 1) for i, (w, p) in enumerate(sent)]
    out = []
    for node in _chunk(toks):
        if node.is_leaf():
            out.append(((node.value, node.label), "O"))
        else:
            leaves = node.leaves()
            out.append(((leaves[0].value, leaves[0].label),
                        "B-" + node.label))
            out.extend(((l.value, l.label), "I-" + node.label)
                       for l in leaves[1:])
    return out


def main():
    train = [to_bio(s) for s in TRAIN]
    heldout = [to_bio(s) for s in HELDOUT]
    model = PerceptronChunker.train(train, epochs=10, seed=0)
    right = total = 0
    for sent in heldout:
        got = model.tag([item for item, _ in sent])
        for (_, gold), (_, guess) in zip(sent, got):
            right += gold == guess
            total += 1
    acc = right / total
    print(f"held-out BIO accuracy {acc:.3f} ({right}/{total})")
    # gate BEFORE writing: a regressed retrain must not clobber the
    # committed fixture
    assert acc >= 0.9, "chunker fixture regressed below 90% held-out"
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tests", "fixtures",
        "chunk_model.json.gz")
    model.save(out)
    print(f"model -> {out}")


if __name__ == "__main__":
    main()
