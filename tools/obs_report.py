"""Combined observability report: host spans + device ops + metrics.

One place that joins the three telemetry surfaces PR 6 standardized:

  * host span timeline (obs.trace.Tracer / a saved Chrome trace JSON) —
    aggregated per span name: count, total/mean/p99 ms;
  * span-derived latency decomposition (`obs.decompose`): each served
    request's total attributed to queue-wait / prefill / decode /
    scheduling-gap phases, aggregated per phase — included automatically
    whenever the spans contain `serve.request` lanes;
  * the device-op table from `optimize.profiler.summarize_trace` (an
    xplane/trace capture directory, when one exists);
  * one or more metrics snapshots (`ServingMetrics.snapshot()` dicts or
    a `MetricsRegistry.snapshot()`), None-guarded via the shared
    `obs.registry.fmt` helper.

`tools/serve_ab.py` routes its per-arm summaries through
`format_report` (replacing its print-only paths), and the CLI below
renders a saved trace + profile dir + metrics JSON from disk:

    python tools/obs_report.py --trace /tmp/serve.trace.json \
        [--profile /tmp/prof] [--metrics /tmp/snapshot.json]

`--trace` repeats: two or more saved traces are stitched on their
`clock_sync` wall-clock anchors into ONE Perfetto-loadable file
(`obs.fleet.merge_traces` — per-instance process groups, shared trace
ids intact), written next to the report (`--merged-trace` overrides
the path) and used as the report's span input — so a migrated
request's cross-server timeline feeds the same span summary and
decomposition a single-server trace does.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from deeplearning4j_tpu.obs.registry import fmt, percentile  # noqa: E402


def _normalize_spans(spans_or_trace):
    """-> list of (name, dur_ms) from a Tracer, a list of Span tuples,
    or a Chrome trace dict ({"traceEvents": [...]})."""
    if spans_or_trace is None:
        return []
    if hasattr(spans_or_trace, "spans"):        # Tracer
        spans_or_trace = spans_or_trace.spans()
    if isinstance(spans_or_trace, dict):        # chrome trace JSON
        return [(e.get("name", "?"), e.get("dur", 0) / 1e3)
                for e in spans_or_trace.get("traceEvents", [])
                if e.get("ph") == "X"]
    out = []
    for s in spans_or_trace:                    # Span namedtuples
        out.append((s.name, s.dur_ns / 1e6))
    return out


def span_summary(spans_or_trace):
    """Per-name aggregation of host spans, sorted by total time desc:
    [{"name", "count", "total_ms", "mean_ms", "p99_ms"}]."""
    durs = defaultdict(list)
    for name, ms in _normalize_spans(spans_or_trace):
        durs[name].append(ms)
    rows = []
    for name, ds in durs.items():
        ds.sort()
        rows.append({"name": name, "count": len(ds),
                     "total_ms": fmt(sum(ds)),
                     "mean_ms": fmt(sum(ds) / len(ds)),
                     "p99_ms": fmt(percentile(ds, 99))})
    rows.sort(key=lambda r: -(r["total_ms"] or 0.0))
    return rows


def build_report(spans=None, profile_logdir=None, metrics=None):
    """Assemble the combined report dict. `metrics` is a snapshot dict
    or {label: snapshot}; `profile_logdir` is summarized when readable
    (missing/unparsable traces degrade to None, never raise — the host
    report must survive a profile that was never captured)."""
    report = {"spans": span_summary(spans) if spans is not None else None,
              "device_ops": None, "metrics": None, "decomposition": None}
    if spans is not None:
        from deeplearning4j_tpu.obs.decompose import decompose
        dec = decompose(spans)
        if dec["n_requests"]:
            report["decomposition"] = dec
    if profile_logdir is not None:
        try:
            from deeplearning4j_tpu.optimize.profiler import \
                summarize_trace
            report["device_ops"] = summarize_trace(profile_logdir)
        except Exception as e:      # no trace / no schema: degrade
            report["device_ops_error"] = str(e)
    if metrics is not None:
        if metrics and not any(isinstance(v, dict)
                               for v in metrics.values()):
            metrics = {"metrics": metrics}
        report["metrics"] = {
            label: {k: fmt(v, 4) for k, v in snap.items()}
            for label, snap in metrics.items()}
    return report


def merge_trace_files(paths, names=None):
    """Load N saved Chrome traces and stitch them on their clock_sync
    anchors (`obs.fleet.merge_traces`) — the multi-`--trace` plumbing,
    importable so tools/fleet_report.py and tests share it."""
    from deeplearning4j_tpu.obs.fleet import merge_traces
    traces = []
    for p in paths:
        with open(p) as fh:
            traces.append(json.load(fh))
    return merge_traces(traces, names=names)


def _table(rows, cols, title, limit=None):
    out = [f"== {title} =="]
    if not rows:
        out.append("  (none)")
        return out
    widths = {c: max(len(c), *(len(str(r.get(c))) for r in rows))
              for c in cols}
    out.append("  " + "  ".join(c.ljust(widths[c]) for c in cols))
    for r in rows[:limit]:
        out.append("  " + "  ".join(
            str(r.get(c)).ljust(widths[c]) for c in cols))
    if limit is not None and len(rows) > limit:
        out.append(f"  ... {len(rows) - limit} more")
    return out


def format_report(report, top=20):
    """Human-readable text rendering of `build_report`'s dict."""
    lines = []
    if report.get("spans") is not None:
        lines += _table(report["spans"],
                        ["name", "count", "total_ms", "mean_ms",
                         "p99_ms"], "host spans", limit=top)
    if report.get("decomposition"):
        dec = report["decomposition"]
        rows = [{"phase": ph, **stats,
                 "fraction": dec["fractions"].get(ph)}
                for ph, stats in dec["phases"].items()]
        lines += _table(rows, ["phase", "total_ms", "mean_ms", "p50_ms",
                               "p99_ms", "fraction"],
                        f"latency decomposition "
                        f"({dec['n_requests']} requests)")
    if report.get("device_ops") is not None:
        lines += _table(report["device_ops"],
                        ["name", "total_ms", "count", "pct"],
                        "device ops", limit=top)
    elif report.get("device_ops_error"):
        lines.append(f"== device ops ==\n  unavailable: "
                     f"{report['device_ops_error']}")
    if report.get("metrics"):
        for label, snap in report["metrics"].items():
            lines.append(f"== metrics: {label} ==")
            for k in sorted(snap):
                lines.append(f"  {k} = {snap[k]}")
    return "\n".join(lines)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--trace", action="append", default=None,
                    help="saved Chrome trace JSON (Tracer.save output); "
                         "repeat to stitch multiple traces on their "
                         "clock_sync anchors into one merged trace")
    ap.add_argument("--merged-trace", default=None,
                    help="where to write the merged trace when more "
                         "than one --trace is given (default: "
                         "<first-trace>.merged.json)")
    ap.add_argument("--profile", help="jax.profiler logdir to summarize")
    ap.add_argument("--metrics", help="metrics snapshot JSON file")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as JSON instead of text")
    args = ap.parse_args()
    spans = None
    if args.trace and len(args.trace) > 1:
        spans = merge_trace_files(args.trace)
        out = args.merged_trace or args.trace[0] + ".merged.json"
        with open(out, "w") as fh:
            json.dump(spans, fh)
        print(f"merged trace ({len(args.trace)} inputs) -> {out}",
              file=sys.stderr)
    elif args.trace:
        with open(args.trace[0]) as fh:
            spans = json.load(fh)
    metrics = None
    if args.metrics:
        with open(args.metrics) as fh:
            metrics = json.load(fh)
    report = build_report(spans=spans, profile_logdir=args.profile,
                          metrics=metrics)
    print(json.dumps(report) if args.json else format_report(report))


if __name__ == "__main__":
    main()
