"""Train the committed POS-model fixture (tests/fixtures/pos_model.json.gz).

The corpus below is a small hand-tagged PTB-tagset sample authored for this
repo (the role OpenNLP's training corpora play for the reference's
en-pos-maxent.bin). Rerun after changing the tagger or corpus:

    python tools/train_pos_fixture.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deeplearning4j_tpu.text.pos_model import PerceptronPosTagger  # noqa: E402


def _parse(block):
    """'word/TAG word/TAG ...' lines -> [[(word, tag)]]."""
    out = []
    for line in block.strip().splitlines():
        line = line.strip()
        if not line:
            continue
        out.append([tuple(tok.rsplit("/", 1)) for tok in line.split()])
    return out


TRAIN = _parse("""
the/DT cat/NN sat/VBD on/IN the/DT mat/NN ./.
a/DT dog/NN chased/VBD the/DT quick/JJ fox/NN ./.
she/PRP reads/VBZ a/DT good/JJ book/NN every/DT day/NN ./.
they/PRP are/VBP walking/VBG to/TO the/DT old/JJ market/NN ./.
he/PRP will/MD buy/VB three/CD new/JJ cars/NNS tomorrow/NN ./.
John/NNP gave/VBD Mary/NNP a/DT small/JJ gift/NN ./.
the/DT children/NNS played/VBD happily/RB in/IN the/DT park/NN ./.
we/PRP have/VBP seen/VBN many/JJ beautiful/JJ birds/NNS ./.
i/PRP can/MD run/VB very/RB fast/RB ./.
the/DT weather/NN was/VBD cold/JJ and/CC windy/JJ yesterday/NN ./.
my/PRP$ brother/NN works/VBZ at/IN a/DT big/JJ bank/NN ./.
students/NNS should/MD study/VB hard/RB for/IN exams/NNS ./.
the/DT red/JJ car/NN stopped/VBD near/IN the/DT bridge/NN ./.
birds/NNS fly/VBP south/RB in/IN winter/NN ./.
this/DT machine/NN makes/VBZ strange/JJ noises/NNS ./.
Sarah/NNP quickly/RB finished/VBD her/PRP$ long/JJ report/NN ./.
the/DT team/NN has/VBZ won/VBN five/CD games/NNS ./.
old/JJ houses/NNS need/VBP constant/JJ repairs/NNS ./.
he/PRP was/VBD eating/VBG lunch/NN with/IN his/PRP$ friends/NNS ./.
the/DT river/NN flows/VBZ slowly/RB through/IN the/DT valley/NN ./.
you/PRP must/MD clean/VB your/PRP$ room/NN today/NN ./.
two/CD large/JJ ships/NNS arrived/VBD at/IN the/DT port/NN ./.
the/DT teacher/NN explained/VBD the/DT difficult/JJ lesson/NN ./.
it/PRP rains/VBZ heavily/RB during/IN the/DT summer/NN ./.
farmers/NNS grow/VBP rice/NN and/CC wheat/NN here/RB ./.
the/DT small/JJ girl/NN smiled/VBD at/IN her/PRP$ mother/NN ./.
Tom/NNP and/CC Anna/NNP visited/VBD the/DT museum/NN ./.
these/DT flowers/NNS bloom/VBP early/RB in/IN spring/NN ./.
the/DT committee/NN will/MD discuss/VB the/DT plan/NN ./.
he/PRP dropped/VBD the/DT heavy/JJ box/NN suddenly/RB ./.
wolves/NNS hunt/VBP in/IN organized/VBN packs/NNS ./.
the/DT new/JJ president/NN promised/VBD major/JJ changes/NNS ./.
she/PRP is/VBZ writing/VBG another/DT mystery/NN novel/NN ./.
workers/NNS built/VBD a/DT tall/JJ tower/NN quickly/RB ./.
the/DT library/NN opens/VBZ at/IN nine/CD ./.
i/PRP saw/VBD a/DT movie/NN about/IN ancient/JJ Rome/NNP ./.
dogs/NNS bark/VBP loudly/RB at/IN strangers/NNS ./.
the/DT price/NN of/IN oil/NN rose/VBD sharply/RB ./.
many/JJ people/NNS enjoy/VBP quiet/JJ evenings/NNS ./.
the/DT artist/NN painted/VBD a/DT wonderful/JJ portrait/NN ./.
we/PRP were/VBD waiting/VBG for/IN the/DT late/JJ train/NN ./.
the/DT company/NN sells/VBZ modern/JJ furniture/NN ./.
children/NNS learn/VBP languages/NNS easily/RB ./.
a/DT strong/JJ wind/NN damaged/VBD several/JJ roofs/NNS ./.
the/DT doctor/NN examined/VBD the/DT young/JJ patient/NN carefully/RB ./.
lions/NNS sleep/VBP during/IN the/DT hot/JJ afternoon/NN ./.
the/DT students/NNS asked/VBD interesting/JJ questions/NNS ./.
her/PRP$ garden/NN looks/VBZ lovely/JJ in/IN June/NNP ./.
the/DT train/NN from/IN Boston/NNP arrived/VBD on/IN time/NN ./.
he/PRP repaired/VBD the/DT broken/VBN fence/NN yesterday/NN ./.
our/PRP$ neighbors/NNS moved/VBD to/TO Chicago/NNP last/JJ month/NN ./.
the/DT chef/NN cooked/VBD a/DT delicious/JJ meal/NN ./.
bees/NNS make/VBP sweet/JJ honey/NN from/IN flowers/NNS ./.
the/DT judge/NN listened/VBD to/TO both/DT sides/NNS patiently/RB ./.
snow/NN covered/VBD the/DT entire/JJ village/NN ./.
the/DT gardener/NN watered/VBD the/DT dry/JJ plants/NNS ./.
he/PRP painted/VBD his/PRP$ house/NN white/JJ ./.
she/PRP lost/VBD her/PRP$ silver/JJ ring/NN ./.
the/DT boy/NN kicked/VBD a/DT red/JJ ball/NN ./.
green/JJ leaves/NNS fall/VBP in/IN autumn/NN ./.
tall/JJ trees/NNS grow/VBP near/IN the/DT river/NN ./.
the/DT engine/NN started/VBD loudly/RB ./.
the/DT old/JJ engine/NN failed/VBD again/RB ./.
we/PRP live/VBP here/RB now/RB ./.
the/DT store/NN is/VBZ closed/VBN now/RB ./.
they/PRP washed/VBD their/PRP$ dirty/JJ clothes/NNS ./.
the/DT player/NN caught/VBD the/DT ball/NN easily/RB ./.
a/DT white/JJ ball/NN rolled/VBD down/IN the/DT hill/NN ./.
the/DT hunter/NN followed/VBD the/DT deer/NN quietly/RB ./.
his/PRP$ answer/NN surprised/VBD the/DT whole/JJ class/NN ./.
her/PRP$ dress/NN matched/VBD her/PRP$ blue/JJ shoes/NNS ./.
the/DT cook/NN tasted/VBD the/DT hot/JJ soup/NN ./.
strong/JJ horses/NNS pulled/VBD the/DT heavy/JJ cart/NN ./.
the/DT clerk/NN counted/VBD the/DT money/NN twice/RB ./.
wild/JJ geese/NNS crossed/VBD the/DT grey/JJ sky/NN ./.
the/DT nurse/NN helped/VBD the/DT injured/VBN man/NN ./.
my/PRP$ sister/NN cleaned/VBD her/PRP$ small/JJ desk/NN ./.
the/DT crowd/NN cheered/VBD very/RB loudly/RB ./.
young/JJ plants/NNS need/VBP water/NN daily/RB ./.
the/DT manager/NN signed/VBD the/DT final/JJ contract/NN ./.
the/DT hungry/JJ dog/NN barked/VBD loudly/RB ./.
a/DT hungry/JJ cat/NN waited/VBD near/IN the/DT door/NN ./.
the/DT brown/JJ dog/NN ran/VBD across/IN the/DT yard/NN ./.
her/PRP$ dog/NN sleeps/VBZ on/IN the/DT soft/JJ couch/NN ./.
""")

HELDOUT = _parse("""
the/DT old/JJ farmer/NN watered/VBD his/PRP$ green/JJ fields/NNS ./.
she/PRP will/MD visit/VB London/NNP in/IN April/NNP ./.
tired/JJ workers/NNS rested/VBD under/IN the/DT tall/JJ trees/NNS ./.
the/DT engine/NN runs/VBZ smoothly/RB now/RB ./.
two/CD boys/NNS kicked/VBD the/DT ball/NN happily/RB ./.
""")


def main():
    model = PerceptronPosTagger.train(TRAIN, epochs=12, seed=0)
    right = total = 0
    for sent in HELDOUT:
        got = model.tag([w for w, _ in sent])
        for (_, gold), (_, guess) in zip(sent, got):
            right += gold == guess
            total += 1
    acc = right / total
    print(f"held-out accuracy {acc:.3f} ({right}/{total})")
    # gate BEFORE writing: a regressed retrain must not clobber the
    # committed fixture
    assert acc >= 0.9, "fixture model regressed below 90% held-out accuracy"
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tests", "fixtures",
        "pos_model.json.gz")
    model.save(out)
    print(f"model -> {out}")


if __name__ == "__main__":
    main()
