"""Background TPU tunnel watcher.

The axon relay (127.0.0.1:8103) is the only path to the chip and can be
down/wedged for hours (see BENCH_r02..r04 history). This loop does a
zero-risk TCP check first and normally spends a real jax-init probe
(subprocess, generous timeout) only when the port accepts — but the
port has been observed both refusing while a client was mid-init and
flapping open with no chip behind it, so it is a heuristic, not a
proven proxy for the axon dial path. Every FORCE_EVERYth iteration the
jax probe therefore runs unconditionally. A timeout-killed probe could
in principle wedge a half-live relay (the reason for the original
TCP-only gate), but a wedged-invisible relay is indistinguishable from
that state from in here, and the forced probes are spaced
FORCE_EVERY*INTERVAL apart (~15 min default) to bound the exposure;
the driver's own bench capture performs the same init+timeout pattern.

Appends one JSON line per probe to /tmp/tpu_probe.log and, when the chip
answers, writes /tmp/tpu_up.json with the device kind so the main agent
can pivot to on-chip measurement.
"""
import json
import os
import socket
import subprocess
import sys
import time

LOG = "/tmp/tpu_probe.log"
UP = "/tmp/tpu_up.json"
PORT = int(os.environ.get("TPU_WATCH_PORT", "8103"))
INTERVAL = int(os.environ.get("TPU_WATCH_INTERVAL_S", "300"))
FORCE_EVERY = max(1, int(os.environ.get("TPU_WATCH_FORCE_EVERY", "3")))
# self-expire so a forgotten watcher's jax-init subprocess can never hold
# a device grant while the driver's end-of-round bench capture probes
MAX_HOURS = float(os.environ.get("TPU_WATCH_MAX_HOURS", "10.5"))
JAX_PROBE_TIMEOUT = int(os.environ.get("TPU_WATCH_PROBE_TIMEOUT_S", "300"))

PROBE_CODE = """
import jax, json
ds = jax.devices()
import jax.numpy as jnp
x = jnp.ones((256, 256), jnp.bfloat16)
y = (x @ x).block_until_ready()
print(json.dumps({"platform": ds[0].platform, "kind": ds[0].device_kind,
                  "n": len(ds), "ok": float(y[0, 0]) == 256.0}))
"""


def log(rec):
    rec["t"] = time.strftime("%H:%M:%S")
    with open(LOG, "a") as f:
        f.write(json.dumps(rec) + "\n")


def tcp_open():
    s = socket.socket()
    s.settimeout(3)
    try:
        s.connect(("127.0.0.1", PORT))
        return True
    except OSError:
        return False
    finally:
        s.close()


def stale_up():
    """Remove the up-marker: a later-wedged tunnel must not leave a
    permanently fresh-looking 'chip is up' signal for the consumer."""
    try:
        os.remove(UP)
    except OSError:
        pass


def main():
    it = 0
    t0 = time.monotonic()   # wall-clock steps must not extend the expiry
    # deadline excludes a worst-case in-flight probe + sleep so no probe
    # subprocess can still be holding a device grant past MAX_HOURS;
    # clamped so a tiny MAX_HOURS still watches at least one iteration
    budget = max(MAX_HOURS * 3600 - JAX_PROBE_TIMEOUT - INTERVAL,
                 INTERVAL + 1)
    while time.monotonic() - t0 < budget:
        it += 1
        # The TCP gate is a cheap heuristic, but the relay port is not a
        # proven proxy for the axon dial path (r5 continuation session:
        # the port flapped open once with no chip behind it, and refused
        # while a live client was mid-init — distinct boots of this
        # container behave differently). Every FORCE_EVERYth iteration
        # run the real jax probe regardless, so a recovery the TCP layer
        # can't see is still caught within ~3 intervals.
        force = it % FORCE_EVERY == 0
        tcp = tcp_open() if not force else None
        if not force and not tcp:
            log({"status": "no-relay"})
            stale_up()
        else:
            try:
                p = subprocess.run(
                    [sys.executable, "-c", PROBE_CODE],
                    capture_output=True, text=True, timeout=JAX_PROBE_TIMEOUT,
                )
                if p.returncode == 0 and p.stdout.strip():
                    info = json.loads(p.stdout.strip().splitlines()[-1])
                    info["probed_at"] = time.time()
                    info["forced"] = force
                    log({"status": "tpu-up", **info})
                    with open(UP, "w") as f:
                        json.dump(info, f)
                else:
                    log({"status": "probe-failed", "rc": p.returncode,
                         "forced": force, "err": p.stderr[-400:]})
                    stale_up()
            except subprocess.TimeoutExpired:
                log({"status": "probe-timeout", "forced": force})
                stale_up()
            except Exception as e:  # keep the watcher alive no matter what
                log({"status": "watcher-error", "err": repr(e)})
                stale_up()      # errors must not preserve an old UP marker
        time.sleep(INTERVAL)
    # expiry must not leave a stale chip-is-up signal behind either
    stale_up()
    log({"status": "expired", "after_s": round(time.monotonic() - t0)})


if __name__ == "__main__":
    main()
