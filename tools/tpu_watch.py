"""Background TPU tunnel watcher.

The axon relay (127.0.0.1:8103) is the only path to the chip and can be
down/wedged for hours (see BENCH_r02..r04 history). This loop does a
zero-risk TCP check first; only when the port accepts does it spend a
real jax-init probe (subprocess, generous timeout — killing a chip job
can wedge the relay, so we only probe when the TCP layer looks alive).

Appends one JSON line per probe to /tmp/tpu_probe.log and, when the chip
answers, writes /tmp/tpu_up.json with the device kind so the main agent
can pivot to on-chip measurement.
"""
import json
import os
import socket
import subprocess
import sys
import time

LOG = "/tmp/tpu_probe.log"
UP = "/tmp/tpu_up.json"
PORT = int(os.environ.get("TPU_WATCH_PORT", "8103"))
INTERVAL = int(os.environ.get("TPU_WATCH_INTERVAL_S", "300"))
JAX_PROBE_TIMEOUT = int(os.environ.get("TPU_WATCH_PROBE_TIMEOUT_S", "300"))

PROBE_CODE = """
import jax, json
ds = jax.devices()
import jax.numpy as jnp
x = jnp.ones((256, 256), jnp.bfloat16)
y = (x @ x).block_until_ready()
print(json.dumps({"platform": ds[0].platform, "kind": ds[0].device_kind,
                  "n": len(ds), "ok": float(y[0, 0]) == 256.0}))
"""


def log(rec):
    rec["t"] = time.strftime("%H:%M:%S")
    with open(LOG, "a") as f:
        f.write(json.dumps(rec) + "\n")


def tcp_open():
    s = socket.socket()
    s.settimeout(3)
    try:
        s.connect(("127.0.0.1", PORT))
        return True
    except OSError:
        return False
    finally:
        s.close()


def stale_up():
    """Remove the up-marker: a later-wedged tunnel must not leave a
    permanently fresh-looking 'chip is up' signal for the consumer."""
    try:
        os.remove(UP)
    except OSError:
        pass


def main():
    while True:
        if not tcp_open():
            log({"status": "no-relay"})
            stale_up()
        else:
            try:
                p = subprocess.run(
                    [sys.executable, "-c", PROBE_CODE],
                    capture_output=True, text=True, timeout=JAX_PROBE_TIMEOUT,
                )
                if p.returncode == 0 and p.stdout.strip():
                    info = json.loads(p.stdout.strip().splitlines()[-1])
                    info["probed_at"] = time.time()
                    log({"status": "tpu-up", **info})
                    with open(UP, "w") as f:
                        json.dump(info, f)
                else:
                    log({"status": "probe-failed", "rc": p.returncode,
                         "err": p.stderr[-400:]})
                    stale_up()
            except subprocess.TimeoutExpired:
                log({"status": "probe-timeout"})
                stale_up()
            except Exception as e:  # keep the watcher alive no matter what
                log({"status": "watcher-error", "err": repr(e)})
        time.sleep(INTERVAL)


if __name__ == "__main__":
    main()
