"""Fleet observability report: N instances -> one merged view.

The replica-fleet rendering of `tools/obs_report.py`: federate N
serving instances' metrics with kind-correct semantics
(`obs.fleet.FleetView` — counters sum, gauges stay per-instance,
histogram buckets merge element-wise), stitch their saved traces on
the `clock_sync` wall-clock anchors into ONE Perfetto file with
per-instance process groups (`obs.fleet.merge_traces`), and render:

  * the PER-INSTANCE table (completed / tokens / SLO attainment /
    service rate / sheds / shed share — the imbalance read-out);
  * the FLEET aggregates (`fleet_slo_attainment`,
    `fleet_goodput_tokens_per_sec`, `fleet_service_rate`,
    `autoscale_decision`, ... — the always-present federation keys
    pinned in tests/test_obs.py);
  * the combined obs_report (span summary + latency decomposition
    over the MERGED trace + per-instance metric sections) through the
    existing `tools/obs_report.py` machinery.

In-process (what `tools/load_sweep.py --fleet N` uses):

    report, merged = build_fleet_report(
        {name: srv.metrics for name, srv in fleet},
        traces=[t.chrome_trace() for t in tracers])

From disk (scraped `/metrics` text expositions + saved traces):

    python tools/fleet_report.py \
        --prom i0=/tmp/i0.prom --prom i1=/tmp/i1.prom \
        --trace /tmp/i0.trace.json --trace /tmp/i1.trace.json \
        --out /tmp/fleet

`--strip-template` (default `dl4j_tpu_serving_{name}_`) removes each
instance's exposition namespace so metric names line up across the
fleet — the same names an in-process `ServingMetrics.kind_snapshot()`
exports.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from deeplearning4j_tpu.obs.fleet import (SHED_KEYS,  # noqa: E402
                                          FleetView, merge_traces)
from deeplearning4j_tpu.obs.registry import fmt  # noqa: E402

_TOOLS = os.path.dirname(os.path.abspath(__file__))
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)

from obs_report import _table, build_report, format_report  # noqa: E402



def build_fleet_report(members, traces=None, trace_names=None,
                       signal=None, strip_template=None):
    """Assemble the fleet report. `members` maps instance name ->
    federation source (ServingMetrics / MetricsRegistry / kind-snapshot
    dict / Prometheus text); `traces` is an optional list of Chrome
    trace dicts stitched into the merged trace. Returns
    (report_dict, merged_trace_or_None) — the merged trace stays out
    of the report dict (it is the big artifact; callers write it next
    to the report)."""
    fv = FleetView(signal=signal)
    for name, src in members.items():
        strip = (strip_template.format(name=name)
                 if strip_template else "")
        fv.add(name, src, strip_prefix=strip)
    fleet = fv.snapshot()
    rows = []
    for inst in fv.instances:
        flat = fv.flat(inst)
        slo_total = flat.get("slo_total") or 0
        rows.append({
            "instance": inst,
            "completed": flat.get("completed"),
            "tokens_out": flat.get("tokens_out"),
            "slo_attainment": fmt(
                (flat.get("slo_met") or 0) / slo_total
                if slo_total else None, 4),
            "service_rate": fmt(
                flat.get("service_rate_tokens_per_sec"), 1),
            "sheds": sum(flat.get(k) or 0 for k in SHED_KEYS),
            "shed_share": fmt(
                fleet["fleet_shed_share"].get(inst), 3),
            "ttft_ms_p99": fmt(flat.get("ttft_ms_p99")),
        })
    # one trace feeds the report AS-IS (no pid rewrite, no merged
    # near-duplicate artifact — the help text promises the merged
    # trace only for >= 2 inputs); two or more stitch on the anchors
    merged, spans = None, None
    if traces:
        ts = list(traces)
        if len(ts) > 1:
            merged = merge_traces(ts, names=trace_names)
            spans = merged
        else:
            spans = ts[0]
    base = build_report(
        spans=spans,
        metrics={inst: fv.flat(inst) for inst in fv.instances})
    return ({"fleet": fleet, "per_instance": rows,
             "report": base}, merged)


# the FleetManager's control-plane event counters (serving/fleet.py),
# rendered as their own section ahead of the aggregate dump — the
# spawn/drain/death/failover/rollback history is the first thing an
# operator reads off a fleet that misbehaved
CONTROL_KEYS = ("fleet_replica_spawned", "fleet_replica_drained",
                "fleet_replica_dead", "fleet_failover_resubmitted",
                "fleet_canary_rollbacks", "fleet_wire_reconnects",
                "fleet_wire_retries", "fleet_migrate_refused",
                "fleet_manager_epoch", "fleet_replicas_adopted",
                "fleet_fenced_ops", "fleet_journal_records",
                # prefix-affinity routing + the fleet prefix tier
                # (serving/fleet.py affinity policy, ISSUE 20):
                # routing verdicts and cross-replica block traffic
                "fleet_routed_affinity", "fleet_routed_spill",
                "fleet_prefix_pull_hits", "fleet_prefix_pull_refused",
                "fleet_prefix_pull_bytes")

# blast-radius containment (serving/fleet.py ISSUE 17): quarantine
# verdicts, the spawn circuit breaker, the shared retry budget, and
# degraded-mode time — the "how contained was the damage" read-out
CONTAINMENT_KEYS = ("fleet_requests_quarantined",
                    "fleet_breaker_open_total", "fleet_breaker_state",
                    "fleet_retry_budget_exhausted",
                    "fleet_degraded_mode_ticks", "fleet_infant_deaths")


def format_fleet_report(report, top=20):
    """Human-readable rendering: per-instance table, fleet-control
    events, fleet aggregates, then the combined obs_report text
    (merged-trace span summary + decomposition + per-instance metric
    sections)."""
    lines = _table(report["per_instance"],
                   ["instance", "completed", "tokens_out",
                    "slo_attainment", "service_rate", "sheds",
                    "shed_share", "ttft_ms_p99"],
                   "fleet instances")
    fleet = report["fleet"]
    lines.append("== fleet control ==")
    for k in CONTROL_KEYS:
        lines.append(f"  {k} = {fleet.get(k, 0)}")
    lines.append("== containment ==")
    for k in CONTAINMENT_KEYS:
        lines.append(f"  {k} = {fleet.get(k, 0)}")
    lines.append("== fleet aggregates ==")
    for k in sorted(fleet):
        if k == "fleet_shed_share" or k in CONTROL_KEYS \
                or k in CONTAINMENT_KEYS:
            continue        # rendered above
        v = fleet[k]
        lines.append(f"  {k} = {fmt(v, 4) if isinstance(v, float) else v}")
    lines.append(format_report(report["report"], top=top))
    return "\n".join(lines)


def main():
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--prom", action="append", default=[],
                    metavar="NAME=PATH",
                    help="instance name = path to its scraped /metrics "
                         "text exposition; repeat per instance")
    ap.add_argument("--trace", action="append", default=[],
                    help="saved Chrome trace JSON; repeat per instance "
                         "(stitched on clock_sync anchors)")
    ap.add_argument("--strip-template",
                    default="dl4j_tpu_serving_{name}_",
                    help="per-instance exposition prefix to strip "
                         "({name} substituted); pass '' to keep names")
    ap.add_argument("--out", default=None,
                    help="write report JSON/text (+ merged trace when "
                         ">=2 --trace) under this path prefix")
    ap.add_argument("--json", action="store_true",
                    help="print the report as JSON instead of text")
    args = ap.parse_args()
    members = {}
    for spec in args.prom:
        name, _, path = spec.partition("=")
        if not path:
            ap.error(f"--prom needs NAME=PATH, got {spec!r}")
        with open(path) as fh:
            members[name] = fh.read()
    traces = []
    for p in args.trace:
        with open(p) as fh:
            traces.append(json.load(fh))
    report, merged = build_fleet_report(
        members, traces=traces or None,
        strip_template=args.strip_template or None)
    if args.out:
        with open(args.out + ".json", "w") as fh:
            json.dump(report, fh)
        with open(args.out + ".txt", "w") as fh:
            fh.write(format_fleet_report(report) + "\n")
        if merged is not None:
            with open(args.out + ".trace.merged.json", "w") as fh:
                json.dump(merged, fh)
    print(json.dumps(report) if args.json
          else format_fleet_report(report))


if __name__ == "__main__":
    main()
