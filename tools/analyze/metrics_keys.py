"""graftlint pass 4 — metrics-keys.

The serving snapshot surface is pinned by two tuples in
tests/test_obs.py (``PINNED_KEYS`` / ``FLEET_PINNED_KEYS``): every
dashboard, sweep tool, and A/B reads those names. The pin test proves
the keys EXIST at runtime; nothing proved the lists and the code
could not drift structurally — a key added to the pin tuple with a
typo'd registration would only fail when some runtime path happened
to exercise it. This pass closes that statically:

* extract every metric name the code can produce from the configured
  source files: ``.count("name")`` call sites (including the eager
  for-loop-over-literal-tuple creation idiom), registry registrations
  (``res(prefix + "name")`` / ``hist(prefix + "name")`` — the
  BinOp's literal suffix), snapshot-dict writes (``out["name"] =`` /
  ``out.setdefault("name", ...)``), and prefix-composed writes
  (``snap["fleet_" + key]`` with ``key`` looping over a literal
  tuple);
* histogram/reservoir base names combine with the derived-quantile
  suffixes (``_p50``/``_p99``/``_mean``/``_count``/``_last``/
  ``_max``) snapshot() emits for them;
* **unregistered-pin** (error): a pinned key with NO producing site.
* **unpinned-stable-key** (warning): an always-present
  ``out.setdefault("k", ...)`` key in ``ServingMetrics.snapshot``
  missing from PINNED_KEYS — the surface grew without growing the
  contract (the reverse drift).

Configured in layers.toml ``[metrics_keys]``: `sources` (files the
names are extracted from), `pins_file` + `pins` (where the tuples
live).
"""
from __future__ import annotations

import ast

PASS = "metrics-keys"

_SUFFIXES = ("_p50", "_p99", "_mean", "_count", "_last", "_max")
_REGISTER_FUNCS = {"res", "hist", "counter", "gauge", "histogram",
                   "reservoir"}


def _finding(path, line, key, message, severity="error"):
    from .core import Finding
    return Finding(PASS, severity, path, line, key, message)


def _const_str(node):
    return node.value if isinstance(node, ast.Constant) and \
        isinstance(node.value, str) else None


def _loop_values(fn):
    """var name -> tuple of literal strings, for every `for var in
    ("a", "b", ...)` in `fn` — resolves the eager-creation and
    prefix-overlay idioms."""
    out = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.For) and \
                isinstance(node.target, ast.Name) and \
                isinstance(node.iter, (ast.Tuple, ast.List)):
            vals = [_const_str(e) for e in node.iter.elts]
            if all(v is not None for v in vals):
                out[node.target.id] = tuple(vals)
    return out


def _key_values(node, loops):
    """Literal string value(s) of a dict-key / call-arg expression:
    a Constant, a Name bound by a literal loop, or a BinOp
    concatenation of those. Returns a list (possibly empty)."""
    s = _const_str(node)
    if s is not None:
        return [s]
    if isinstance(node, ast.Name) and node.id in loops:
        return list(loops[node.id])
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        lefts = _key_values(node.left, loops)
        rights = _key_values(node.right, loops)
        return [a + b for a in lefts for b in rights]
    return []


def extract_names(files):
    """(direct_names, base_names): every producible metric/snapshot
    key, and the histogram/reservoir bases that imply derived
    suffix keys."""
    direct, bases = set(), set()
    for src in files:
        for fn in [n for n in ast.walk(src.tree)
                   if isinstance(n, ast.FunctionDef)]:
            loops = _loop_values(fn)
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    parts = []
                    f = node.func
                    while isinstance(f, ast.Attribute):
                        parts.append(f.attr)
                        f = f.value
                    name = parts[0] if parts else (
                        f.id if isinstance(f, ast.Name) else None)
                    if name == "count" and node.args:
                        direct.update(_key_values(node.args[0],
                                                  loops))
                    elif name == "setdefault" and node.args:
                        direct.update(_key_values(node.args[0],
                                                  loops))
                    elif name in _REGISTER_FUNCS and node.args:
                        # res(p + "latency_ms") — the literal suffix
                        # of the BinOp is the base name
                        arg = node.args[0]
                        if isinstance(arg, ast.BinOp) and \
                                isinstance(arg.op, ast.Add):
                            s = _const_str(arg.right)
                            if s is not None:
                                bases.add(s)
                        else:
                            s = _const_str(arg)
                            if s is not None:
                                bases.add(s)
                elif isinstance(node, ast.Assign):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Subscript):
                            direct.update(_key_values(tgt.slice,
                                                      loops))
                # dict literals: snapshot dicts built in one
                # expression contribute their keys directly
                # (FleetView.snapshot's `out = {"fleet_instances":
                # ...}`), and histogram-handle dicts
                # (latency_histograms) contribute them as bases for
                # the derived _p50/_p99/... keys
                elif isinstance(node, ast.Dict):
                    for k in node.keys:
                        s = _const_str(k) if k is not None else None
                        if s is not None:
                            direct.add(s)
                            bases.add(s)
    return direct, bases


def extract_pins(pins_src, pin_names):
    """pin tuple name -> (line, tuple of keys) from the pins file."""
    out = {}
    for node in ast.walk(pins_src.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id in pin_names \
                and isinstance(node.value, (ast.Tuple, ast.List)):
            vals = [_const_str(e) for e in node.value.elts]
            if all(v is not None for v in vals):
                out[node.targets[0].id] = (node.lineno, tuple(vals))
    return out


def _stable_setdefault_keys(files):
    """Keys from `out.setdefault("k", <const>)` inside
    ServingMetrics.snapshot — the always-present surface the reverse
    check compares against PINNED_KEYS."""
    keys = set()
    for src in files:
        for cls in [n for n in ast.walk(src.tree)
                    if isinstance(n, ast.ClassDef)
                    and n.name == "ServingMetrics"]:
            for fn in [n for n in cls.body
                       if isinstance(n, ast.FunctionDef)
                       and n.name == "snapshot"]:
                for node in ast.walk(fn):
                    if isinstance(node, ast.Call) and \
                            isinstance(node.func, ast.Attribute) and \
                            node.func.attr == "setdefault" and \
                            node.args:
                        s = _const_str(node.args[0])
                        if s is not None:
                            keys.add(s)
    return keys


def producible(key, direct, bases):
    if key in direct:
        return True
    for suf in _SUFFIXES:
        if key.endswith(suf) and key[:-len(suf)] in bases:
            return True
    return False


def check(config, files):
    cfg = config.metrics
    sources = cfg.get("sources", ["serving/metrics.py",
                                  "serving/fleet.py",
                                  "obs/fleet.py",
                                  "obs/registry.py"])
    pins_file = cfg.get("pins_file", "tests/test_obs.py")
    pin_names = cfg.get("pins", ["PINNED_KEYS", "FLEET_PINNED_KEYS"])
    scoped = config.package_glob(sources, files)
    if not scoped:
        return []                # fixture runs configure explicitly
    from .core import SourceFile
    import os
    pins_path = os.path.join(config.root, pins_file)
    with open(pins_path, encoding="utf-8") as fh:
        pins_src = SourceFile(os.path.relpath(pins_path, config.root),
                              fh.read(), root=config.root)
    return check_extracted(scoped, pins_src, pin_names)


def check_extracted(source_files, pins_src, pin_names):
    """The testable core: sources + a parsed pins file -> findings."""
    direct, bases = extract_names(source_files)
    pins = extract_pins(pins_src, pin_names)
    findings = []
    for pin_name in pin_names:
        if pin_name not in pins:
            findings.append(_finding(
                pins_src.relpath, 1, f"missing-pin-tuple:{pin_name}",
                f"pin tuple {pin_name} not found in "
                f"{pins_src.relpath} — the metrics-keys contract "
                f"lost its anchor"))
            continue
        line, keys = pins[pin_name]
        for key in keys:
            if not producible(key, direct, bases):
                findings.append(_finding(
                    pins_src.relpath, line,
                    f"unregistered-pin:{key}",
                    f"pinned snapshot key '{key}' ({pin_name}) has "
                    f"no producing site in the metrics sources — "
                    f"the pin list and the code drifted"))
    # reverse drift: always-present snapshot keys not pinned
    if "PINNED_KEYS" in pins:
        _, keys = pins["PINNED_KEYS"]
        pinned = set(keys)
        for key in sorted(_stable_setdefault_keys(source_files)):
            if key not in pinned:
                findings.append(_finding(
                    pins_src.relpath, pins["PINNED_KEYS"][0],
                    f"unpinned-stable-key:{key}",
                    f"always-present snapshot key '{key}' "
                    f"(setdefault in ServingMetrics.snapshot) is "
                    f"missing from PINNED_KEYS — the export surface "
                    f"grew without growing the contract",
                    severity="warning"))
    return findings
