"""graftlint pass 2 — future-hygiene.

The repo's worst failure class is a STRANDED CALLER: a
`concurrent.futures.Future` someone is waiting on that nobody will
ever resolve. This pass checks every function that creates a Future
locally (``fut = cf.Future()`` — attribute-stored creations like
``self.ack = cf.Future()`` escape at birth and are out of scope):

* **future-leak** (error): on every control-flow path from creation
  to a NORMAL function exit (fall-through or `return` of something
  else), the future must be RESOLVED (`set_result` / `set_exception`
  / `cancel`) or ESCAPE — returned, stored into an attribute/
  container, or passed to a call (ownership transfer: whoever
  received it is now responsible). A path that exits via `raise` is
  fine: the caller got the exception, nobody holds the future.
* **future-swallowed-exception** (warning): an `except` handler that
  can be entered while the future is pending, swallows the exception
  (no re-raise, no return/resolution of the future), after which the
  future still escapes — the classic shape where the success path
  resolves but the error path parks a forever-pending future in a
  registry. This is the "including exception paths" half of the
  check, scoped to where it is decidable.

The analysis is a statement-level abstract interpretation over a
two-point lattice per tracked future ({pending, safe}), with branch
join = pending-if-any-branch-pending, proper try/except/finally
modeling (handler entry state = the pessimistic join over the try
body), and loops processed twice (enough for a monotone two-point
lattice to reach fixpoint). Generators and async functions are
skipped — their suspension points make "exit" a different concept.
"""
from __future__ import annotations

import ast

PASS = "future-hygiene"

_RESOLVERS = {"set_result", "set_exception", "cancel",
              "set_running_or_notify_cancel"}
_FUTURE_CTORS = {"Future"}

PENDING, SAFE = 0, 1


def _finding(severity, path, line, key, message):
    from .core import Finding
    return Finding(PASS, severity, path, line, key, message)


def _is_future_ctor(value):
    if not isinstance(value, ast.Call):
        return False
    node = value.func
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return bool(parts) and parts[0] in _FUTURE_CTORS


def _name_used(node, name):
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id == name:
            return True
    return False


class _Tracker:
    """Abstract interpretation for ONE tracked future variable in one
    function. `state` is PENDING/SAFE/None (None: not yet created).
    Exit states at normal exits are recorded with their line."""

    def __init__(self, fn, var, create_line, src, findings, where):
        self.fn = fn
        self.var = var
        self.create_line = create_line
        self.src = src
        self.findings = findings
        self.where = where
        self.bad_exits = []      # (line, kind) pending at normal exit
        self.swallows = []       # handler lines that swallow pending
        self.escapes_anywhere = self._any_escape(fn)

    # -- event classification ------------------------------------------
    def _any_escape(self, fn):
        for node in ast.walk(fn):
            if self._escape_event(node):
                return True
        return False

    def _resolve_event(self, stmt):
        """var.set_result/set_exception/cancel anywhere in stmt."""
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _RESOLVERS and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id == self.var:
                return True
        return False

    def _escape_event(self, node):
        """The future leaves this function's ownership: stored into an
        attribute/subscript, passed as a call argument (append, wait,
        a resolver helper like `_fail_future(fut, exc)`), or part of
        a returned/stored tuple/list/dict."""
        if isinstance(node, ast.Call):
            for arg in list(node.args) + [kw.value
                                          for kw in node.keywords]:
                if _name_used(arg, self.var):
                    return True
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, (ast.Attribute, ast.Subscript)) \
                        and _name_used(node.value, self.var):
                    return True
            # rebinding another NAME to the future aliases it; treat
            # as escape (tracking aliases is out of scope — absorbing
            # the imprecision as "safe" avoids false leaks)
            if any(isinstance(t, ast.Name) and t.id != self.var
                   for t in node.targets) \
                    and _name_used(node.value, self.var):
                return True
        return False

    def _stmt_makes_safe(self, stmt):
        if self._resolve_event(stmt):
            return True
        for node in ast.walk(stmt):
            if self._escape_event(node):
                return True
        return False

    # -- interpretation ------------------------------------------------
    def run(self):
        state = self._block(self.fn.body, None)
        if state == PENDING:
            last = self.fn.body[-1]
            self.bad_exits.append((last.lineno, "fall-through"))
        for line, kind in self.bad_exits:
            self.findings.append(_finding(
                "error", self.src.relpath, line,
                f"future-leak:{self.where}:{self.var}",
                f"Future `{self.var}` (created at line "
                f"{self.create_line} in {self.where}) can reach the "
                f"{kind} exit at line {line} unresolved and "
                f"unreturned — a caller holding it would wait "
                f"forever; resolve it, return it, or hand it off on "
                f"every path"))
        for line in self.swallows:
            self.findings.append(_finding(
                "warning", self.src.relpath, line,
                f"future-swallowed-exception:{self.where}:{self.var}",
                f"except handler at line {line} swallows an "
                f"exception while Future `{self.var}` may be "
                f"pending, and the future escapes this function — "
                f"the error path must fail the future loudly "
                f"(set_exception) or re-raise"))

    def _block(self, body, state):
        """Returns the state after `body` (None = not created yet;
        'exit' states from return/raise are recorded eagerly)."""
        for stmt in body:
            state = self._stmt(stmt, state)
            if state == "dead":
                return "dead"
        return state

    def _stmt(self, stmt, state):
        # creation site
        if isinstance(stmt, ast.Assign) and \
                len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name) and \
                stmt.targets[0].id == self.var and \
                _is_future_ctor(stmt.value):
            return PENDING
        if isinstance(stmt, ast.Return):
            if stmt.value is not None and \
                    _name_used(stmt.value, self.var):
                return "dead"            # returned: caller owns it
            if state == PENDING:
                self.bad_exits.append((stmt.lineno, "return"))
            return "dead"
        if isinstance(stmt, ast.Raise):
            return "dead"                # caller gets the exception
        if isinstance(stmt, (ast.Break, ast.Continue)):
            return state                 # loop-local; approximated
        if isinstance(stmt, ast.If):
            s_then = self._block(stmt.body, state)
            s_else = self._block(stmt.orelse, state)
            return self._join(s_then, s_else)
        if isinstance(stmt, (ast.While, ast.For)):
            # two passes reach fixpoint on a two-point lattice; the
            # zero-iteration path keeps the incoming state
            s1 = self._block(stmt.body, state)
            s2 = self._block(stmt.body, self._join(state, s1))
            out = self._join(state, s2)
            return self._block(stmt.orelse, out)
        if isinstance(stmt, ast.With):
            return self._block(stmt.body, state)
        if isinstance(stmt, ast.Try):
            return self._try(stmt, state)
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return state
        # simple statement: resolve/escape events apply
        if state == PENDING and self._stmt_makes_safe(stmt):
            return SAFE
        return state

    def _try(self, stmt, state):
        body_state = self._block(stmt.body, state)
        # a handler can be entered from ANY point in the body: its
        # entry state is the pessimistic join over the whole region
        handler_entry = self._join(state, body_state)
        out_states = []
        if body_state != "dead":
            out_states.append(self._block(stmt.orelse, body_state))
        for handler in stmt.handlers:
            h_state = self._block(handler.body, handler_entry)
            if h_state == "dead":
                continue
            if handler_entry == PENDING and h_state == PENDING \
                    and self.escapes_anywhere:
                self.swallows.append(handler.lineno)
            out_states.append(h_state)
        merged = "dead"
        for s in out_states:
            merged = self._join(merged, s)
        final = self._block(stmt.finalbody, merged)
        return final

    @staticmethod
    def _join(a, b):
        if a == "dead":
            return b
        if b == "dead":
            return a
        if a is None:
            return b
        if b is None:
            return a
        return min(a, b)         # PENDING wins


def check(config, files):
    scoped = config.package_glob(config.future_modules, files)
    if not scoped:
        scoped = files
    findings = []
    for src in scoped:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.FunctionDef):
                continue
            if any(isinstance(n, (ast.Yield, ast.YieldFrom))
                   for n in ast.walk(node)):
                continue         # generators: "exit" means suspension
            created = {}
            for stmt in ast.walk(node):
                if isinstance(stmt, ast.Assign) and \
                        len(stmt.targets) == 1 and \
                        isinstance(stmt.targets[0], ast.Name) and \
                        _is_future_ctor(stmt.value):
                    var = stmt.targets[0].id
                    created.setdefault(var, stmt.lineno)
            for var, line in sorted(created.items()):
                where = node.name
                _Tracker(node, var, line, src, findings, where).run()
    return findings
