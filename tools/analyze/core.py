"""graftlint core: findings, suppressions, baseline, config, runner.

The repo's load-bearing concurrency/layering/metrics conventions used
to live only in scattered test pins and docstring promises ("never
block while holding the dispatch lock", "obs/ never imports jax",
"every admitted future resolves", "the pinned snapshot keys exist").
This package machine-checks them: four stdlib-only AST passes over the
`deeplearning4j_tpu` package, run as a tier-1 test
(tests/test_analyze.py) and as a CLI (`python -m tools.analyze`).

Model
-----
* A `Finding` is one violation: pass name, severity, file, line, a
  STABLE `key` (identity that survives line moves — used for the
  baseline), and a human message.
* Inline suppression: a ``# graftlint: disable=<pass>[,<pass>] --
  <justification>`` comment on the offending line (or the line
  directly above it) suppresses that pass there. The justification is
  MANDATORY: a disable comment without one is itself a finding
  (pass ``suppression``) — the acceptance rule "every suppression
  carries a one-line justification", machine-enforced.
* Baseline: ``tools/analyze/baseline.json`` holds fingerprints of
  grandfathered findings (each with a reason). Baselined findings are
  reported separately and do not fail the run; NEW findings do. The
  shipped baseline is empty — everything real was fixed or
  inline-suppressed in the PR that introduced the suite — but the
  mechanism exists so a future pass can be landed strict-for-new-code
  before the backlog is paid down.

Config lives in ``tools/analyze/layers.toml`` (the layer map plus the
per-pass module scopes). Python 3.10 has no tomllib, so `_read_toml`
parses the small TOML subset the config uses (tables, arrays of
tables, string/bool/int scalars, arrays of strings) — stdlib-only is a
hard requirement here: the analyzer must run in any environment that
can parse the source, including ones without jax/numpy.
"""
from __future__ import annotations

import ast
import fnmatch
import json
import os
import re

__all__ = ["Finding", "Config", "SourceFile", "load_config", "run",
           "Report", "repo_root"]

SEVERITIES = ("error", "warning", "info")

# the suppression marker: `# graftlint: disable=pass-a,pass-b -- why`
_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable=([a-z0-9_,\-\s]+?)"
    r"(?:--\s*(.*?))?\s*$")


class Finding:
    """One violation. `key` is the line-number-free identity used for
    baseline fingerprints; `fingerprint` prefixes it with pass + path
    so identical keys in different files never collide."""

    __slots__ = ("pass_name", "severity", "path", "line", "key",
                 "message")

    def __init__(self, pass_name, severity, path, line, key, message):
        assert severity in SEVERITIES, severity
        self.pass_name = pass_name
        self.severity = severity
        self.path = path
        self.line = int(line)
        self.key = key
        self.message = message

    @property
    def fingerprint(self):
        return f"{self.pass_name}:{self.path}:{self.key}"

    def as_dict(self):
        return {"pass": self.pass_name, "severity": self.severity,
                "path": self.path, "line": self.line, "key": self.key,
                "fingerprint": self.fingerprint,
                "message": self.message}

    def __repr__(self):
        return (f"<{self.severity} {self.pass_name} "
                f"{self.path}:{self.line} {self.key}>")


class SourceFile:
    """One parsed module: path (repo-relative, '/'-separated), source,
    AST, and the per-line suppression map."""

    def __init__(self, relpath, source, root=""):
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.root = root
        self.tree = ast.parse(source, filename=relpath)
        # line -> (set of pass names or {"all"}, has_justification)
        self.suppressions = {}
        for i, text in enumerate(source.splitlines(), start=1):
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            passes = {p.strip() for p in m.group(1).split(",")
                      if p.strip()}
            reason = (m.group(2) or "").strip()
            self.suppressions[i] = (passes, bool(reason))

    def suppressed(self, pass_name, line):
        """True when `pass_name` is disabled at `line` — a marker on
        the line itself or on the (comment) line directly above."""
        for ln in (line, line - 1):
            entry = self.suppressions.get(ln)
            if entry and (pass_name in entry[0] or "all" in entry[0]):
                return True
        return False

    def suppression_findings(self):
        """Every disable marker missing its `-- justification` is a
        finding: the suppression policy is part of the contract."""
        out = []
        for line, (passes, has_reason) in sorted(
                self.suppressions.items()):
            if not has_reason:
                out.append(Finding(
                    "suppression", "error", self.relpath, line,
                    f"missing-justification:L{line}",
                    f"graftlint disable={','.join(sorted(passes))} "
                    f"has no '-- <justification>' — every suppression "
                    f"must say why"))
        return out


# ---------------------------------------------------------------------------
# config (layers.toml) — minimal TOML subset reader
# ---------------------------------------------------------------------------
def _parse_value(raw):
    raw = raw.strip()
    if raw.startswith("[") and raw.endswith("]"):
        inner = raw[1:-1].strip()
        if not inner:
            return []
        out, cur, in_str, quote = [], "", False, ""
        for ch in inner:
            if in_str:
                if ch == quote:
                    in_str = False
                else:
                    cur += ch
            elif ch in "\"'":
                in_str, quote = True, ch
            elif ch == ",":
                if cur.strip() or cur:
                    out.append(cur)
                cur = ""
            else:
                if ch.strip():
                    raise ValueError(f"bad array element near {raw!r}")
        if cur:
            out.append(cur)
        return out
    if raw.startswith(("\"", "'")) and raw.endswith(raw[0]):
        return raw[1:-1]
    if raw in ("true", "false"):
        return raw == "true"
    return int(raw)


def _read_toml(text):
    """The TOML subset layers.toml uses: `[table]`, `[[array-table]]`,
    `key = value` with string/bool/int/array-of-string values; arrays
    may span lines until the closing bracket. Comments start with #
    outside strings."""
    root = {}
    current = root
    pending_key, pending_buf = None, ""
    for rawline in text.splitlines():
        line = _strip_comment(rawline)
        if pending_key is not None:
            pending_buf += " " + line.strip()
            if _array_closed(pending_buf):
                current[pending_key] = _parse_value(pending_buf)
                pending_key, pending_buf = None, ""
            continue
        line = line.strip()
        if not line:
            continue
        if line.startswith("[["):
            name = line[2:line.index("]]")].strip()
            current = {}
            root.setdefault(name, []).append(current)
        elif line.startswith("["):
            name = line[1:line.index("]")].strip()
            current = root.setdefault(name, {})
        else:
            key, _, val = line.partition("=")
            key, val = key.strip(), val.strip()
            if val.startswith("[") and not _array_closed(val):
                pending_key, pending_buf = key, val
            else:
                current[key] = _parse_value(val)
    if pending_key is not None:
        raise ValueError(f"unterminated array for key {pending_key!r}")
    return root


def _strip_comment(line):
    out, in_str, quote = "", False, ""
    for ch in line:
        if in_str:
            out += ch
            if ch == quote:
                in_str = False
        elif ch in "\"'":
            in_str, quote = True, ch
            out += ch
        elif ch == "#":
            break
        else:
            out += ch
    return out


def _array_closed(buf):
    depth, in_str, quote = 0, False, ""
    for ch in buf:
        if in_str:
            if ch == quote:
                in_str = False
        elif ch in "\"'":
            in_str, quote = True, ch
        elif ch == "[":
            depth += 1
        elif ch == "]":
            depth -= 1
    return depth == 0


class Config:
    """Parsed layers.toml plus resolved paths. `package` is the
    repo-relative package dir every `modules =` glob is rooted at."""

    def __init__(self, data, root):
        self.root = root
        meta = data.get("meta", {})
        self.package = meta.get("package", "deeplearning4j_tpu")
        self.layers = data.get("layer", [])
        self.lock_modules = data.get("lock_discipline", {}).get(
            "modules", [])
        self.future_modules = data.get("future_hygiene", {}).get(
            "modules", [])
        self.metrics = data.get("metrics_keys", {})

    def package_glob(self, patterns, files):
        """Files (SourceFile list) whose package-relative path matches
        any of `patterns` (globs rooted at the package dir)."""
        prefix = self.package + "/"
        out = []
        for f in files:
            if not f.relpath.startswith(prefix):
                continue
            rel = f.relpath[len(prefix):]
            if any(fnmatch.fnmatch(rel, p) for p in patterns):
                out.append(f)
        return out


def repo_root():
    """The repository root: two levels above this file (tools/analyze)."""
    return os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", ".."))


def load_config(path=None, root=None):
    root = root if root is not None else repo_root()
    path = path if path is not None else os.path.join(
        os.path.dirname(__file__), "layers.toml")
    with open(path) as fh:
        return Config(_read_toml(fh.read()), root)


# ---------------------------------------------------------------------------
# source collection + runner
# ---------------------------------------------------------------------------
def collect_sources(root, paths=None, package="deeplearning4j_tpu"):
    """SourceFile list for the analysis set: every .py under the
    package (skipping __pycache__), or exactly `paths` when given."""
    files = []
    if paths:
        for p in paths:
            ap = p if os.path.isabs(p) else os.path.join(root, p)
            if os.path.isdir(ap):
                for dirpath, dirnames, names in os.walk(ap):
                    dirnames[:] = [d for d in dirnames
                                   if d != "__pycache__"]
                    files.extend(os.path.join(dirpath, n)
                                 for n in sorted(names)
                                 if n.endswith(".py"))
            else:
                files.append(ap)
    else:
        pkg = os.path.join(root, package)
        for dirpath, dirnames, names in os.walk(pkg):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            files.extend(os.path.join(dirpath, n)
                         for n in sorted(names) if n.endswith(".py"))
    out = []
    for ap in sorted(set(files)):
        rel = os.path.relpath(ap, root)
        with open(ap, encoding="utf-8") as fh:
            out.append(SourceFile(rel, fh.read(), root=root))
    return out


class Report:
    """One analyzer run: active findings (fail the build), inline-
    suppressed, baselined, and the counts the CLI/CI artifact needs."""

    def __init__(self, active, suppressed, baselined, files):
        self.active = active
        self.suppressed = suppressed
        self.baselined = baselined
        self.files = files

    def as_dict(self):
        return {
            "files_checked": len(self.files),
            "active": [f.as_dict() for f in self.active],
            "suppressed": [f.as_dict() for f in self.suppressed],
            "baselined": [f.as_dict() for f in self.baselined],
            "counts": {"active": len(self.active),
                       "suppressed": len(self.suppressed),
                       "baselined": len(self.baselined)},
        }


def load_baseline(path=None):
    path = path if path is not None else os.path.join(
        os.path.dirname(__file__), "baseline.json")
    if not os.path.exists(path):
        return {}
    with open(path) as fh:
        data = json.load(fh)
    return {e["fingerprint"]: e.get("reason", "")
            for e in data.get("findings", [])}


def write_baseline(findings, path):
    data = {"findings": [
        {"fingerprint": f.fingerprint,
         "reason": "grandfathered at baseline creation"}
        for f in sorted(findings, key=lambda f: f.fingerprint)]}
    with open(path, "w") as fh:
        json.dump(data, fh, indent=2, sort_keys=True)
        fh.write("\n")


def run(config=None, paths=None, baseline=None, passes=None):
    """One full analysis. `baseline` is a fingerprint->reason dict ({}
    disables), None loads the checked-in file. `passes` filters by
    pass name (None = all four + the suppression policy check)."""
    from . import futures, layering, lockcheck, metrics_keys
    config = config if config is not None else load_config()
    files = collect_sources(config.root, paths=paths,
                            package=config.package)
    baseline = baseline if baseline is not None else load_baseline()
    by_path = {f.relpath: f for f in files}

    all_findings = []
    if passes is None or "lock-discipline" in passes:
        all_findings += lockcheck.check(config, files)
    if passes is None or "future-hygiene" in passes:
        all_findings += futures.check(config, files)
    if passes is None or "layering" in passes:
        all_findings += layering.check(config, files)
    if passes is None or "metrics-keys" in passes:
        all_findings += metrics_keys.check(config, files)
    if passes is None or "suppression" in passes:
        for f in files:
            all_findings += f.suppression_findings()

    active, suppressed, baselined = [], [], []
    for f in sorted(all_findings, key=lambda f: (f.path, f.line,
                                                 f.key)):
        src = by_path.get(f.path)
        if src is not None and f.pass_name != "suppression" \
                and src.suppressed(f.pass_name, f.line):
            suppressed.append(f)
        elif f.fingerprint in baseline:
            baselined.append(f)
        else:
            active.append(f)
    return Report(active, suppressed, baselined, files)
