"""graftlint — stdlib-only static analysis for the repo's concurrency,
layering, and metrics invariants.

Four passes (see each module's docstring for the precise rules and
their documented heuristics):

    lock-discipline   blocking calls under a held lock; lock-order
                      cycles (tools/analyze/lockcheck.py)
    future-hygiene    locally-created Futures must resolve/escape on
                      every path (tools/analyze/futures.py)
    layering          the declared import-layer map, layers.toml
                      (tools/analyze/layering.py)
    metrics-keys      PINNED_KEYS/FLEET_PINNED_KEYS vs the code's
                      producible names (tools/analyze/metrics_keys.py)

Plus the suppression-policy check: every inline
``# graftlint: disable=<pass> -- <justification>`` must carry its
justification.

Usage:

    python -m tools.analyze             # human-readable, exit 1 on
                                        # any unsuppressed finding
    python -m tools.analyze --json      # machine-readable (CI artifact)
    python -m tools.analyze path.py ... # restrict the analyzed set

In-process (the tier-1 test and the layering-pin wrappers):

    from tools.analyze import run
    report = run()                      # Report: .active/.suppressed/
                                        # .baselined
"""
from .core import (Config, Finding, Report, load_config, repo_root,
                   run)
from .layering import check_rules as check_layer_rules

__all__ = ["Config", "Finding", "Report", "load_config", "repo_root",
           "run", "check_layer_rules"]
