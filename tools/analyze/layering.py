"""graftlint pass 3 — layering.

The repo's import-layering conventions used to live as per-test regex
pins (tests/test_obs.py and tests/test_fleet.py each grepped their
module for ``import jax``). This pass replaces them with ONE declared
contract: ``tools/analyze/layers.toml`` lists layer rules —

    [[layer]]
    name    = "obs-stdlib-only"
    modules = ["obs/*.py"]            # globs, package-relative
    deny    = ["jax", "numpy"]        # absolute module prefixes
    allow   = ["trace.py = numpy"]    # per-file exceptions
    reason  = "why this layer exists"

and the pass resolves EVERY import in every matched file — top-level
and function-local, `import x` and `from x import y`, relative
imports resolved against the file's package path — and flags any that
lands under a denied prefix without a matching allow entry. The old
test names survive as thin wrappers over this pass (layers.toml is
the single source of truth; see tests/test_obs.py / test_fleet.py).

Deny prefixes match on dotted-path boundaries: deny "jax" matches
"jax" and "jax.numpy", never "jaxtyping". Relative imports inside the
package resolve to their absolute names first, so deny
"deeplearning4j_tpu.parallel" catches ``from ..parallel import x``
too.
"""
from __future__ import annotations

import ast
import fnmatch

PASS = "layering"


def _finding(path, line, key, message, severity="error"):
    from .core import Finding
    return Finding(PASS, severity, path, line, key, message)


def resolve_imports(relpath, tree):
    """Yield (line, absolute_module_name) for every import statement
    in `tree` — top-level and function-local. `relpath` is the repo-
    relative file path ('/'-separated) relative imports resolve
    against. `from X import y` yields both X and X.y (y may be a
    submodule — the prefix match must see it either way)."""
    pkg_parts = relpath.split("/")[:-1]     # the file's package dirs
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                yield node.lineno, alias.name
        elif isinstance(node, ast.ImportFrom):
            if node.level == 0:
                base = node.module or ""
            else:
                # level=1 is the file's own package, each extra level
                # climbs one package up (same for modules and
                # __init__.py given pkg_parts is the DIRECTORY path)
                anchor = pkg_parts[:len(pkg_parts)
                                   - (node.level - 1)]
                base = ".".join(anchor + ([node.module]
                                          if node.module else []))
            if not base:
                continue
            yield node.lineno, base
            for alias in node.names:
                if alias.name != "*":
                    yield node.lineno, f"{base}.{alias.name}"


def _denied(module, deny):
    for prefix in deny:
        if module == prefix or module.startswith(prefix + "."):
            return prefix
    return None


def _parse_allow(entries):
    """['file-glob = module-prefix', ...] -> [(glob, prefix)]."""
    out = []
    for e in entries:
        left, _, right = e.partition("=")
        out.append((left.strip(), right.strip()))
    return out


def check(config, files):
    findings = []
    prefix = config.package + "/"
    for rule in config.layers:
        name = rule.get("name", "unnamed")
        patterns = rule.get("modules", [])
        deny = rule.get("deny", [])
        allow = _parse_allow(rule.get("allow", []))
        reason = rule.get("reason", "")
        for src in config.package_glob(patterns, files):
            rel = src.relpath[len(prefix):] \
                if src.relpath.startswith(prefix) else src.relpath
            for line, module in resolve_imports(src.relpath,
                                                src.tree):
                hit = _denied(module, deny)
                if hit is None:
                    continue
                if any(fnmatch.fnmatch(rel, g)
                       and (module == p
                            or module.startswith(p + "."))
                       for g, p in allow):
                    continue
                why = f" ({reason})" if reason else ""
                findings.append(_finding(
                    src.relpath, line,
                    f"layer:{name}:{module}",
                    f"layer rule '{name}': {src.relpath} imports "
                    f"`{module}` (denied prefix `{hit}`){why} — "
                    f"either the import moves, or layers.toml "
                    f"grows an explicit allow entry"))
    return findings


def check_rules(rule_names, config=None):
    """Run ONLY the named layer rules over the repo and return their
    findings — the hook tests/test_obs.py and tests/test_fleet.py
    wrap so the old no-jax-import pins stay as named tests while
    layers.toml is the single source of truth. Raises KeyError when a
    named rule does not exist (a renamed rule must fail the wrapper
    test loudly, not pass vacuously)."""
    from .core import collect_sources, load_config
    config = config if config is not None else load_config()
    have = {r.get("name") for r in config.layers}
    missing = set(rule_names) - have
    if missing:
        raise KeyError(
            f"layer rule(s) {sorted(missing)} not found in "
            f"layers.toml (have: {sorted(have)})")
    sub = Subset(config, [r for r in config.layers
                          if r.get("name") in set(rule_names)])
    files = collect_sources(config.root, package=config.package)
    return check(sub, files)


class Subset:
    """Config view exposing only a subset of layer rules."""

    def __init__(self, config, layers):
        self._config = config
        self.layers = layers

    def __getattr__(self, name):
        return getattr(self._config, name)
