"""graftlint pass 1 — lock-discipline.

Two rules over the threaded modules (scoped by
``[lock_discipline] modules`` in layers.toml):

* **blocking-under-lock** — a call that can block on IO, a peer
  thread, or the clock must not run while a lock is held: the wedge
  class behind every "faulthandler dump of a hung run" bug. Direct
  primitives (socket send/recv/accept/connect, ``Future.result``,
  ``Queue.join``/blocking ``get``, ``Thread.join``, ``Event.wait``,
  ``cf.wait``, ``time.sleep``) are errors; calls that reach a
  primitive TRANSITIVELY through a helper/method (resolved by name
  across the analyzed set — conservative on purpose) are warnings.
* **lock-order-cycle** — the union lock-acquisition graph (edges:
  lock B acquired — lexically or through a called method — while lock
  A is held) must be acyclic, or two threads taking the locks in
  opposite orders can deadlock.

How types are known (all heuristic, all documented here because a
linter that cannot explain its verdicts teaches nobody):

* ``self.X = threading.Lock()/RLock()`` (and Queue/Thread/Event/
  socket/Future constructors) in any class body of the analyzed set
  binds attribute name X to that type — and the attribute NAME is
  then trusted globally, so ``conn.wlock`` is a lock because `_Conn`
  declares ``wlock`` as one. Collisions resolve conservatively (a
  lock-typed declaration wins).
* Local variables assigned from a typed constructor or a typed
  attribute inherit the type inside that function; parameters named
  ``sock``/``conn`` are assumed sockets (the module convention).
* A module function or method whose body contains a blocking
  primitive is itself blocking; one fixpoint propagates this through
  same-set calls BY NAME (``self._await_ack`` blocks wherever it
  resolves, because the one definition that exists blocks on
  ``Future.result``). By-name resolution over-approximates — the
  right direction for a deadlock linter; the inline suppression
  mechanism absorbs the deliberate cases (per-connection write
  mutexes, the single-reconnector latch).

Held-region modeling: ``with self.X:`` blocks; explicit
``lock.acquire()`` holds from the next statement until the first
statement containing the matching ``release()`` (the try/finally
idiom); ``with self._foo_lock(key):`` — a method call whose name
contains "lock" — is treated as acquiring a synthetic per-call lock
(the parameter-server per-worker lock pattern). Lambda and nested-def
bodies are NOT scanned at the call site — they run later, usually not
under the lock.
"""
from __future__ import annotations

import ast
import itertools

PASS = "lock-discipline"

_TYPE_CTORS = {
    "Lock": "lock", "RLock": "lock", "Queue": "queue",
    "LifoQueue": "queue", "PriorityQueue": "queue",
    "SimpleQueue": "queue", "Thread": "thread", "Event": "event",
    "Condition": "lock", "Semaphore": "lock", "Future": "future",
    "socket": "socket", "create_connection": "socket",
}

# receiver-type -> method names that block. `put` is deliberately
# absent: the repo's bounded queues only ever put_nowait, and an
# unbounded queue's put never blocks — flagging every put would be
# noise without a boundedness analysis.
_BLOCKING_METHODS = {
    "socket": {"send", "sendall", "recv", "accept", "connect",
               "recv_into", "makefile"},
    "queue": {"join", "get"},
    "thread": {"join"},
    "event": {"wait"},
    "future": {"result", "exception"},
}
_NONBLOCKING = {"get_nowait", "put_nowait", "task_done", "qsize",
                "empty", "full", "done", "cancel", "set", "clear",
                "is_set", "locked"}
_SOCKET_PARAM_NAMES = ("sock", "conn")


def _calls_in(node):
    """Every Call that executes when `node` does: walks the tree but
    prunes Lambda and nested function/class bodies (they run later)."""
    stack = [node]
    while stack:
        cur = stack.pop()
        if isinstance(cur, (ast.Lambda, ast.FunctionDef,
                            ast.AsyncFunctionDef, ast.ClassDef)) \
                and cur is not node:
            continue
        if isinstance(cur, ast.Call):
            yield cur
        stack.extend(ast.iter_child_nodes(cur))


def _call_name(func):
    parts = []
    node = func
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    parts.reverse()
    return parts


def _ctor_type(value):
    if not isinstance(value, ast.Call):
        return None
    parts = _call_name(value.func)
    return _TYPE_CTORS.get(parts[-1]) if parts else None


def _recv_chain(node):
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        return tuple(parts)
    return None


class _ClassInfo:
    def __init__(self, module, name):
        self.module = module
        self.name = name
        self.attr_types = {}     # attr name -> type tag
        self.methods = {}        # method name -> ast def


def _scan_classes(files):
    classes, attr_types = [], {}
    mod_funcs = {}               # (relpath, name) -> def
    for f in files:
        for node in f.tree.body:
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                mod_funcs[(f.relpath, node.name)] = node
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            ci = _ClassInfo(f.relpath, node.name)
            for item in node.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    ci.methods[item.name] = item
                    for sub in ast.walk(item):
                        if isinstance(sub, ast.Assign):
                            t = _ctor_type(sub.value)
                            if t is None:
                                continue
                            for tgt in sub.targets:
                                ch = _recv_chain(tgt)
                                if ch and len(ch) == 2 \
                                        and ch[0] == "self":
                                    ci.attr_types[ch[1]] = t
            classes.append(ci)
            for attr, t in ci.attr_types.items():
                if attr_types.get(attr) is None or t == "lock":
                    attr_types[attr] = t
    return classes, attr_types, mod_funcs


def _local_types(fn, attr_types):
    out = {}
    for arg in list(fn.args.args) + list(fn.args.kwonlyargs):
        if arg.arg in _SOCKET_PARAM_NAMES:
            out[arg.arg] = "socket"
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            t = _ctor_type(node.value)
            if t is None:
                ch = _recv_chain(node.value)
                if ch and len(ch) >= 2:
                    t = attr_types.get(ch[-1])
            if t is None:
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out[tgt.id] = t
    return out


def _receiver_type(func, attr_types, local_types):
    if not isinstance(func, ast.Attribute):
        return None
    ch = _recv_chain(func.value)
    if ch is None:
        return None
    if len(ch) == 1:
        return local_types.get(ch[0])
    t = attr_types.get(ch[-1])
    if t is None and ch[-1] in ("sock", "_sock"):
        t = "socket"
    return t


def _is_blocking_call(call, attr_types, local_types, blocking_names):
    """('direct'|'transitive'|None, label)."""
    parts = _call_name(call.func)
    if not parts:
        return None, None
    last = parts[-1]
    if last in _NONBLOCKING:
        return None, None
    if last == "sleep" and (len(parts) == 1 or parts[-2] == "time"):
        return "direct", "time.sleep"
    if last == "wait" and len(parts) >= 2 \
            and parts[-2] in ("cf", "futures"):
        return "direct", "futures.wait"
    if last == "create_connection":
        return "direct", "socket.create_connection"
    rt = _receiver_type(call.func, attr_types, local_types)
    if rt is not None:
        # a typed receiver is authoritative: socket.close / thread
        # .start / queue.qsize never block even when some class in
        # the set defines a blocking method of the same name
        if last in _BLOCKING_METHODS.get(rt, ()):
            return "direct", f"{rt}.{last}"
        return None, None
    if last in blocking_names:
        return "transitive", last
    return None, None


def _blocking_fixpoint(classes, mod_funcs, attr_types):
    defs = []
    for ci in classes:
        defs.extend(ci.methods.items())
    for (_, name), fn in mod_funcs.items():
        defs.append((name, fn))
    blocking = set()
    while True:
        grew = False
        for name, fn in defs:
            if name in blocking:
                continue
            local_types = _local_types(fn, attr_types)
            for call in _calls_in(fn):
                kind, _ = _is_blocking_call(call, attr_types,
                                            local_types, blocking)
                if kind is not None:
                    blocking.add(name)
                    grew = True
                    break
        if not grew:
            return blocking


def _lock_id(node, ci, attr_types, classes):
    """'Class.attr' for a known lock expression, else None. A method
    call whose name contains 'lock' (`self._worker_lock(wid)`) gets a
    synthetic per-call id — the keyed-mutex-factory pattern."""
    if isinstance(node, ast.Call):
        parts = _call_name(node.func)
        if parts and "lock" in parts[-1].lower():
            owner = ci.name if ci is not None else "?"
            return f"{owner}.{parts[-1]}()"
        return None
    ch = _recv_chain(node)
    if ch is None or len(ch) < 2:
        return None
    attr = ch[-1]
    if attr_types.get(attr) != "lock":
        return None
    if ch[0] == "self" and len(ch) == 2 and ci is not None \
            and attr in ci.attr_types:
        return f"{ci.name}.{attr}"
    owners = [c.name for c in classes
              if c.attr_types.get(attr) == "lock"]
    if len(owners) == 1:
        return f"{owners[0]}.{attr}"
    return f"?.{attr}"


def _finding(severity, path, line, key, message):
    from .core import Finding
    return Finding(PASS, severity, path, line, key, message)


def _method_lock_sets(classes, attr_types):
    """name -> set of lock ids its body acquires (one level)."""
    out = {}
    for ci in classes:
        for name, fn in ci.methods.items():
            locks = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.With):
                    for item in node.items:
                        lid = _lock_id(item.context_expr, ci,
                                       attr_types, classes)
                        if lid:
                            locks.add(lid)
                elif isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "acquire":
                    lid = _lock_id(node.func.value, ci, attr_types,
                                   classes)
                    if lid:
                        locks.add(lid)
            if locks:
                out.setdefault(name, set()).update(locks)
    return out


class _FnChecker:
    def __init__(self, src, ci, fn, attr_types, classes,
                 blocking_names, method_locks, findings, edges):
        self.src = src
        self.ci = ci
        self.fn = fn
        self.attr_types = attr_types
        self.classes = classes
        self.blocking = blocking_names
        self.method_locks = method_locks
        self.findings = findings
        self.edges = edges       # (lockA, lockB) -> (path, line, fn)
        self.local_types = _local_types(fn, attr_types)
        self.held = []           # lock-id stack
        self.explicit = []       # explicitly acquire()d lock ids

    def run(self):
        self._stmts(self.fn.body)

    # -- lock bookkeeping ----------------------------------------------
    def _acquired(self, lock_id, line):
        where = (f"{self.ci.name if self.ci else '<module>'}"
                 f".{self.fn.name}")
        for h in self.held:
            if h != lock_id:
                self.edges.setdefault(
                    (h, lock_id), (self.src.relpath, line, where))

    def _scan_expr(self, node):
        """Check every call executed by `node` (lambdas pruned)."""
        for call in _calls_in(node):
            self._check_call(call)

    def _check_call(self, call):
        kind, label = _is_blocking_call(
            call, self.attr_types, self.local_types, self.blocking)
        parts = _call_name(call.func)
        if self.held and parts and parts[-1] in self.method_locks:
            for lid in self.method_locks[parts[-1]]:
                if lid not in self.held:
                    self._acquired(lid, call.lineno)
        if kind is None or not self.held:
            return
        where = (f"{self.ci.name + '.' if self.ci else ''}"
                 f"{self.fn.name}")
        sev = "error" if kind == "direct" else "warning"
        verb = ("blocking call" if kind == "direct"
                else "call that can block (via its definition)")
        self.findings.append(_finding(
            sev, self.src.relpath, call.lineno,
            f"blocking-under-lock:{where}:{label}",
            f"{verb} `{label}` while holding {self.held[-1]} in "
            f"{where}() — hoist it out of the critical section or "
            f"suppress with a justification"))

    # -- statement walk ------------------------------------------------
    def _stmts(self, body):
        for stmt in body:
            simple = not isinstance(
                stmt, (ast.With, ast.If, ast.For, ast.While, ast.Try,
                       ast.FunctionDef, ast.AsyncFunctionDef,
                       ast.ClassDef))
            if simple and self.explicit:
                released = [l for l in self.explicit
                            if _contains_release(stmt, l)]
            else:
                released = []
            self._stmt(stmt)
            for lid in released:
                self.explicit.remove(lid)
                if lid in self.held:
                    self.held.remove(lid)
            acq = (_explicit_acquire(stmt, self.ci, self.attr_types,
                                     self.classes)
                   if isinstance(stmt, (ast.If, ast.Expr, ast.Assign,
                                        ast.AugAssign, ast.Return))
                   else None)
            if acq is not None and acq not in self.held:
                self._acquired(acq, stmt.lineno)
                self.held.append(acq)
                self.explicit.append(acq)

    def _stmt(self, stmt):
        if isinstance(stmt, ast.With):
            self._with(stmt)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            pass                 # nested defs run later
        elif isinstance(stmt, ast.If):
            self._scan_expr(stmt.test)
            self._stmts(stmt.body)
            self._stmts(stmt.orelse)
        elif isinstance(stmt, ast.While):
            self._scan_expr(stmt.test)
            self._stmts(stmt.body)
            self._stmts(stmt.orelse)
        elif isinstance(stmt, ast.For):
            self._scan_expr(stmt.iter)
            self._stmts(stmt.body)
            self._stmts(stmt.orelse)
        elif isinstance(stmt, ast.Try):
            self._stmts(stmt.body)
            for handler in stmt.handlers:
                self._stmts(handler.body)
            self._stmts(stmt.orelse)
            self._stmts(stmt.finalbody)
        else:
            self._scan_expr(stmt)

    def _with(self, stmt):
        pushed = []
        for item in stmt.items:
            expr = item.context_expr
            lid = _lock_id(expr, self.ci, self.attr_types,
                           self.classes)
            if lid is not None:
                if isinstance(expr, ast.Call):
                    self._scan_expr(expr)   # the factory call itself
                if lid not in self.held:
                    self._acquired(lid, stmt.lineno)
                    self.held.append(lid)
                    pushed.append(lid)
            else:
                self._scan_expr(expr)       # tracer span, socket, ...
        self._stmts(stmt.body)
        for lid in pushed:
            self.held.remove(lid)


def _contains_release(stmt, lock_id):
    attr = lock_id.split(".")[-1]
    for node in ast.walk(stmt):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "release":
            ch = _recv_chain(node.func.value)
            if ch and ch[-1] == attr:
                return True
    return False


def _explicit_acquire(stmt, ci, attr_types, classes):
    for node in ast.walk(stmt):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "acquire":
            return _lock_id(node.func.value, ci, attr_types, classes)
    return None


def _find_cycles(edges):
    graph = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
    seen_sets = set()
    cycles = []

    def dfs(start, node, path, visited):
        for nxt in sorted(graph.get(node, ())):
            if nxt == start:
                key = frozenset(path)
                if key not in seen_sets:
                    seen_sets.add(key)
                    cycles.append(list(path))
            elif nxt not in visited and len(path) < 8:
                visited.add(nxt)
                dfs(start, nxt, path + [nxt], visited)
                visited.discard(nxt)

    for start in sorted(graph):
        dfs(start, start, [start], {start})
    return cycles


def check(config, files):
    scoped = config.package_glob(config.lock_modules, files)
    if not scoped:
        scoped = files           # fixture runs pass files directly
    classes, attr_types, mod_funcs = _scan_classes(scoped)
    blocking_names = _blocking_fixpoint(classes, mod_funcs,
                                        attr_types)
    method_locks = _method_lock_sets(classes, attr_types)
    findings, edges = [], {}
    for src in scoped:
        for ci in [c for c in classes if c.module == src.relpath]:
            for fn in ci.methods.values():
                _FnChecker(src, ci, fn, attr_types, classes,
                           blocking_names, method_locks, findings,
                           edges).run()
        for (rel, _name), fn in mod_funcs.items():
            if rel == src.relpath:
                _FnChecker(src, None, fn, attr_types, classes,
                           blocking_names, method_locks, findings,
                           edges).run()
    for cycle in _find_cycles(edges):
        loop = " -> ".join(cycle + [cycle[0]])
        site = None
        for a, b in itertools.pairwise(cycle + [cycle[0]]):
            if (a, b) in edges:
                site = edges[(a, b)]
                break
        path, line, where = site if site else ("?", 1, "?")
        findings.append(_finding(
            "error", path, line,
            f"lock-order-cycle:{'>'.join(sorted(set(cycle)))}",
            f"lock acquisition order cycle {loop} (an edge is taken "
            f"in {where}) — two threads taking these locks in "
            f"opposite orders can deadlock"))
    return findings
