"""graftlint CLI: ``python -m tools.analyze [--json] [paths...]``.

Exit code 0 when every finding is inline-suppressed (with a
justification) or baselined; 1 otherwise. ``--json`` emits the full
report (active + suppressed + baselined, with fingerprints) — the CI
artifact tier1.yml uploads per run.
"""
from __future__ import annotations

import argparse
import json
import sys

from .core import load_baseline, load_config, run, write_baseline


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m tools.analyze",
        description="graftlint: the repo's concurrency/layering/"
                    "metrics invariants, machine-checked")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to analyze (default: the whole "
                         "package)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: the checked-in "
                         "tools/analyze/baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: report grandfathered "
                         "findings as active")
    ap.add_argument("--write-baseline", metavar="PATH",
                    help="write the current ACTIVE findings as a new "
                         "baseline to PATH and exit 0")
    ap.add_argument("--pass", dest="passes", action="append",
                    default=None, metavar="NAME",
                    help="run only the named pass (repeatable): "
                         "lock-discipline, future-hygiene, layering, "
                         "metrics-keys, suppression")
    args = ap.parse_args(argv)

    config = load_config()
    baseline = {} if args.no_baseline else load_baseline(
        args.baseline)
    report = run(config=config, paths=args.paths or None,
                 baseline=baseline, passes=args.passes)

    if args.write_baseline:
        write_baseline(report.active, args.write_baseline)
        print(f"wrote {len(report.active)} fingerprints to "
              f"{args.write_baseline}")
        return 0

    if args.json:
        json.dump(report.as_dict(), sys.stdout, indent=2)
        sys.stdout.write("\n")
    else:
        for f in report.active:
            print(f"{f.path}:{f.line}: [{f.severity}] "
                  f"{f.pass_name}: {f.message}")
        print(f"graftlint: {len(report.files)} files, "
              f"{len(report.active)} finding(s) "
              f"({len(report.suppressed)} suppressed, "
              f"{len(report.baselined)} baselined)")
    return 1 if report.active else 0


if __name__ == "__main__":
    sys.exit(main())
