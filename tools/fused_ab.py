"""Fused multi-step dispatch A/B on the CPU backend (no chip needed).

The fused fit loop (net.fused_steps(K), nn/fused.py) exists to amortize
HOST DISPATCH — one jitted-call round-trip per K optimizer steps instead
of per step. On the CPU backend small-model steps are host-overhead-
dominated, so the win is measurable without the chip; this microbench
drives the REAL fit loops (fit(DataSetIterator) / fit(DataSet) TBPTT)
through the interleaved same-process A/B protocol (bench.py
_interleaved_median: alternating short segments, median per arm) and
prints one JSON line per config:

  * mlp_b64        — dispatch-DOMINATED (sub-ms step): where fusing wins
  * lenet_b64_bf16 — compute-dominated on CPU (bf16 conv emulation):
                     where fusing LOSES on this backend, because XLA:CPU
                     runs while-loop bodies single-threaded — a CPU
                     artifact, not a dispatch-model cost (the TPU scan
                     body uses the same hardware as the standalone step)
  * char_rnn_small — 4 fused TBPTT segments per dispatch

Run:  JAX_PLATFORMS=cpu python tools/fused_ab.py [--segments N]
Numbers recorded in PERF.md ("fused multi-step dispatch").
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

K = 8

# the ONE protocol implementation (bench.py is import-safe: no jax at
# import time, __main__ guarded) — a drift between the bench's A/B and
# this microbench would make the PERF.md numbers incomparable
from bench import _interleaved_median as _interleaved  # noqa: E402


def _mlp(seed=7):
    from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater("adam").learning_rate(0.01).list()
            .layer(0, DenseLayer(n_out=64, activation="relu"))
            .layer(1, OutputLayer(n_out=10, activation="softmax",
                                  loss_function="mcxent"))
            .set_input_type(InputType.feed_forward(32))
            .build())
    return MultiLayerNetwork(conf).init()


def bench_fit_iterator(make_net, x, y, n_batches, iters, segments):
    """A/B the iterator-driven fit loop: fused1 vs fused8 over the same
    staged batches, alternating segments, steps/sec medians."""
    import jax

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
    ds = DataSet(jax.device_put(x), jax.device_put(y))
    nets = {"fused1": make_net(), "fused8": make_net().fused_steps(K)}

    def seg(net):
        def run():
            t0 = time.perf_counter()
            for _ in range(iters):
                net.fit(ListDataSetIterator([ds] * n_batches))
            float(net._score)
            return n_batches * iters / (time.perf_counter() - t0)
        return run

    for net in nets.values():      # compile + warm staging off the clock
        seg(net)()
    return _interleaved({n: seg(net) for n, net in nets.items()}, segments)


def config_mlp(segments):
    import numpy as np
    r = np.random.default_rng(0)
    x = r.random((64, 32)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[r.integers(0, 10, 64)]
    ab = bench_fit_iterator(_mlp, x, y, n_batches=2 * K, iters=8,
                            segments=segments)
    return {"config": "mlp_b64 (32-64-10 f32, dispatch-dominated)",
            "unit": "steps/sec", **_verdict(ab)}


def config_lenet(segments):
    import numpy as np

    from deeplearning4j_tpu.models.zoo.lenet import lenet
    r = np.random.default_rng(0)
    x = r.random((64, 784)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[r.integers(0, 10, 64)]
    ab = bench_fit_iterator(lambda: lenet(data_type="bfloat16"), x, y,
                            n_batches=K, iters=1, segments=segments)
    return {"config": "lenet_b64_bf16 (compute-dominated on CPU)",
            "unit": "steps/sec", **_verdict(ab)}


def config_char_rnn(segments):
    import jax
    import numpy as np

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.models.zoo.char_rnn import char_rnn
    r = np.random.default_rng(0)
    V, B, T = 77, 8, 200           # tbptt 50 -> 4 segments per fit
    x = np.eye(V, dtype=np.float32)[r.integers(0, V, (B, T))]
    y = np.eye(V, dtype=np.float32)[r.integers(0, V, (B, T))]
    ds = DataSet(jax.device_put(x), jax.device_put(y))
    nets = {"fused1": char_rnn(data_type="bfloat16"),
            "fused8": char_rnn(data_type="bfloat16").fused_steps(K)}

    def seg(net):
        def run():
            t0 = time.perf_counter()
            for _ in range(3):
                net.fit(ds)
            float(net._score)
            return 3 * 4 / (time.perf_counter() - t0)   # segments/sec
        return run

    for net in nets.values():
        net.fit(ds)
        float(net._score)
    ab = _interleaved({n: seg(net) for n, net in nets.items()}, segments)
    return {"config": "char_rnn_small (B8 T200 tbptt50, 4 fused "
                      "segments/dispatch)",
            "unit": "steps/sec", **_verdict(ab)}


def _verdict(ab):
    speedup = round(ab["fused8"]["median"]
                    / max(ab["fused1"]["median"], 1e-9), 3)
    return {"fused1": ab["fused1"], "fused8": ab["fused8"],
            "fused_speedup": speedup}


def main():
    segments = 5
    if "--segments" in sys.argv:
        segments = int(sys.argv[sys.argv.index("--segments") + 1])
    import jax
    print(json.dumps({"platform": jax.devices()[0].platform,
                      "fused_steps": K, "segments": segments,
                      "protocol": "interleaved same-process A/B, "
                                  "median-of-segments per arm"}),
          flush=True)
    for fn in (config_mlp, config_char_rnn, config_lenet):
        print(json.dumps(fn(segments)), flush=True)


if __name__ == "__main__":
    main()
